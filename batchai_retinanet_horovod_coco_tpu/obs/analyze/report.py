"""The perf doctor: obs artifacts → one machine-readable PERF_REPORT.json.

The read-side half of the observability subsystem (ISSUE 8).  PR 3 made
every run write where-the-time-went evidence — merged Chrome trace,
structured events JSONL, watchdog dumps — but only a human in Perfetto
could interpret it, so nothing ever *named* the hot path the next perf PR
should attack.  This module is that interpreter: a deterministic pure
function from a run's own artifacts to

- **step-time decomposition** — data_wait / compile / step / eval
  fractions of the train-loop window, from the existing span vocabulary;
- **pipeline overlap efficiency** — how well the one-behind eval and
  serve drivers hide device time behind host work, measured as
  ``1 - blocked_fetch_time / pipeline_wall`` over the dispatch/fetch
  span pairs (1.0 = the host never waited on the device);
- **queue-depth stall correlation** — the Chrome counter tracks
  cross-referenced against ``data_wait`` spans: how much of the host's
  blocked time the device-prefetch queue was empty (starved) vs merely
  slow;
- **memory trend** — first/last/peak and bytes-per-second slope of every
  device ``bytes_in_use`` gauge (HBM headroom is peak vs the device's
  capacity; CPU backends report nothing and the section says so);
- **an MFU estimate** — the XLA-counted step FLOPs the train loop records
  at each compile point (``cost_analysis`` trace instants, from the
  unoptimized lowering — no second backend compile) against the device's
  peak TFLOP/s, so the roofline number exists per RUN, not only per
  bench;
- **a ranked top-3 bottleneck verdict** — each entry names the spans to
  stare at in Perfetto and the ``tune/`` problems (``nms``, ``focal``,
  ``matching``, ``batch``) the next optimization PR should search;
- **a numerics section** (ISSUE 10, schema v3) — the numerics flight
  recorder's read-back: per-log-window grad-norm/update-ratio/
  replica-agreement series from the ``numerics`` JSONL records, tripped
  finite-checks from the ``numerics_trip`` trace/JSONL markers, and the
  NUMERICS_DUMP.json cross-reference.  Any trip or non-finite count
  contributes a ``numerics:divergence`` verdict at the absolute head of
  the ranking — a run computing NaNs has no performance question left;
- **an SLO violations section** (ISSUE 9, schema v2) — the
  ``slo_violation`` events the live monitor (obs/slo.py) emitted, read
  from BOTH the events JSONL and the trace's instant markers and
  aggregated per rule.  A violated SLO is a breach someone *declared*
  they care about, so it outranks every inferred bottleneck: each
  violated rule contributes a ``slo:<rule>`` verdict at the head of the
  ranking (score 1.0), with tune ops mapped from the breached metric so
  ``tune --from-report`` still closes the loop.

Determinism contract: the report is a pure function of the artifact
files — no wall clocks, no environment probes (the peak-TFLOPs env
override excepted), floats rounded through one helper — so the inline
auto-emit at ``train.py``/``bench.py`` finalize and the offline CLI
(``python -m batchai_retinanet_horovod_coco_tpu.obs.analyze <obs_dir>``)
produce byte-identical files from the same obs dir (pinned against the
committed fixture in tests/unit/test_analyze.py).

jax-free by design: the analyzer runs on artifacts, not on devices, so
the offline CLI starts in milliseconds and the module obeys the same
import discipline as the rest of obs/.
"""

from __future__ import annotations

import json
import math
import os
import sys
from typing import Any, Iterable

from batchai_retinanet_horovod_coco_tpu.obs.events import (
    latency_percentiles,
    split_runs,
)

# v3 (ISSUE 10): + the ``numerics`` section (grad/update health, trip
# markers, NUMERICS_DUMP cross-reference) and its numerics:* verdicts.
# v2 (ISSUE 9): + the ``violations`` section and its slo:* verdicts.
SCHEMA_VERSION = 3

# Peak dense bf16 TFLOP/s per chip by device kind (public spec sheets) —
# THE table, shared with bench.py's MFU line (one source of truth).
PEAK_TFLOPS = (
    ("v5 lite", 197.0),  # v5e
    ("v5e", 197.0),
    ("v5p", 459.0),
    ("v4", 275.0),
    ("v6", 918.0),  # Trillium
)

# Nominal per-host figure for CPU smokes: MFU against it is order-of-
# magnitude only (the report labels it ``peak_source: "nominal-cpu"``),
# but it keeps the roofline field populated end-to-end on dev boxes.
CPU_NOMINAL_PEAK_TFLOPS = 0.05

# The train loop's top-level span vocabulary (train/loop.py): these names
# partition the loop thread's wall clock, so their fractions + "other"
# sum to ~1 by construction.
_TRAIN_VOCAB = (
    "data_wait",
    "compile_train_step",
    "step",
    "metrics_fetch",
    "eval",
    "final_eval",
)

# Decomposition keys the report always carries (fixed set → stable schema).
_DECOMP_KEYS = ("data_wait", "compile", "step", "metrics_fetch", "eval", "other")

# Span families worth per-family latency stats when present (fixed list →
# deterministic report keys).
_SPAN_STAT_NAMES = (
    "data_wait",
    "step",
    "compile_train_step",
    "metrics_fetch",
    "eval",
    "final_eval",
    "async_eval",
    "detect_dispatch",
    "detect_fetch",
    "eval_convert",
    "eval_score",
    "eval_put_wait",
    "serve_dispatch",
    "serve_fetch",
    "serve_convert",
    "serve_preprocess",
    "pipe_decode_wait",
    "shm_head_wait",
    "shm_assemble",
    "decode",
    "device-prefetch",
    "eval-device-prefetch",
)

# The host-feed queue whose depth the stall correlation reads (the
# device-prefetch thread's counter, data/prefetch.py): data_wait with this
# at 0 is a STARVED pipeline (add workers); data_wait with depth > 0 is a
# transfer/dispatch hiccup.
_FEED_QUEUE = "device-prefetch.qsize"


class AnalyzeError(RuntimeError):
    """Artifact missing/unreadable in a way the caller should surface."""


def _r(x: float | None, nd: int = 6) -> float | None:
    return None if x is None else round(float(x), nd)


def device_peak_tflops(device_kind: str | None) -> tuple[float | None, str | None]:
    """(peak TFLOP/s, provenance) for a device kind.  Provenance is
    ``spec`` (public sheet), ``nominal-cpu`` (order-of-magnitude host
    figure), ``env`` (RETINANET_PEAK_TFLOPS override for kinds the table
    doesn't know), or None/None when unresolvable."""
    if not device_kind:
        return None, None
    kind = device_kind.lower()
    for sub, peak in PEAK_TFLOPS:
        if sub in kind:
            return peak, "spec"
    env = os.environ.get("RETINANET_PEAK_TFLOPS")
    if env:
        try:
            return float(env), "env"
        except ValueError:
            pass
    if "cpu" in kind:
        return CPU_NOMINAL_PEAK_TFLOPS, "nominal-cpu"
    return None, None


# ---------------------------------------------------------------------------
# Artifact loading
# ---------------------------------------------------------------------------


def load_trace(path: str) -> tuple[list[dict], dict]:
    """trace.json → (chrome events, health counters).  Raises AnalyzeError
    on a missing/unreadable file; a structurally odd but parseable file
    degrades to whatever events it carries."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except OSError as e:
        raise AnalyzeError(f"cannot read trace {path!r}: {e}") from e
    except ValueError as e:
        raise AnalyzeError(f"trace {path!r} is not valid JSON: {e}") from e
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        raise AnalyzeError(f"trace {path!r} has no traceEvents list")
    other = doc.get("otherData") or {}
    health = {
        "merged_partials": len(other.get("merged_from") or []),
        "skipped_trace_partials": len(other.get("skipped") or []),
    }
    return [e for e in events if isinstance(e, dict)], health


def _spans_by_name(events: Iterable[dict]) -> dict[str, list[dict]]:
    out: dict[str, list[dict]] = {}
    for e in events:
        if e.get("ph") == "X":
            out.setdefault(e.get("name", "?"), []).append(e)
    return out


def _counters_by_name(events: Iterable[dict]) -> dict[str, list[tuple[float, float]]]:
    """counter name → [(t_s, value)] sorted by time."""
    out: dict[str, list[tuple[float, float]]] = {}
    for e in events:
        if e.get("ph") == "C":
            try:
                v = float((e.get("args") or {})["value"])
            except (KeyError, TypeError, ValueError):
                continue
            out.setdefault(e.get("name", "?"), []).append((e["ts"] / 1e6, v))
    for series in out.values():
        series.sort()
    return out


def _instants(events: Iterable[dict], name: str) -> list[dict]:
    return [
        e for e in events if e.get("ph") == "i" and e.get("name") == name
    ]


def _dur_s(e: dict) -> float:
    return e.get("dur", 0) / 1e6


def _start_s(e: dict) -> float:
    return e["ts"] / 1e6


def _end_s(e: dict) -> float:
    return (e["ts"] + e.get("dur", 0)) / 1e6


# ---------------------------------------------------------------------------
# Sections
# ---------------------------------------------------------------------------


def _steps_section(spans: dict[str, list[dict]]) -> dict | None:
    """Step-time decomposition over the train-loop thread's window.

    The window is the extent of the loop's top-level spans on the track
    that carries the ``step`` spans; those spans never nest among
    themselves (train/loop.py), so their totals plus an explicit
    ``other`` remainder partition the window and the fractions sum to ~1.
    """
    steps = spans.get("step") or []
    if not steps:
        return None
    # The loop thread's track: where the step spans live (a merged trace
    # carries every process; per-(pid,tid) keying keeps e.g. an async-eval
    # thread's spans out of the loop's accounting).
    track_counts: dict[tuple, int] = {}
    for e in steps:
        track_counts[(e.get("pid"), e.get("tid"))] = (
            track_counts.get((e.get("pid"), e.get("tid")), 0) + 1
        )
    # Deterministic tie-break via str() — pid/tid may be absent in
    # hand-built traces and None does not order against ints.
    track = max(track_counts, key=lambda k: (track_counts[k], str(k)))

    def on_track(name: str) -> list[dict]:
        return [
            e
            for e in spans.get(name, [])
            if (e.get("pid"), e.get("tid")) == track
        ]

    vocab = {name: on_track(name) for name in _TRAIN_VOCAB}
    all_spans = [e for group in vocab.values() for e in group]
    window_start = min(_start_s(e) for e in all_spans)
    window_end = max(_end_s(e) for e in all_spans)
    window_s = max(window_end - window_start, 1e-9)

    totals = {name: sum(_dur_s(e) for e in group) for name, group in vocab.items()}
    eval_s = totals["eval"] + totals["final_eval"]
    attributed = {
        "data_wait": totals["data_wait"],
        "compile": totals["compile_train_step"],
        "step": totals["step"],
        "metrics_fetch": totals["metrics_fetch"],
        "eval": eval_s,
    }
    other = max(0.0, window_s - sum(attributed.values()))
    decomposition = {k: _r(v / window_s) for k, v in attributed.items()}
    decomposition["other"] = _r(other / window_s)

    step_track = vocab["step"]
    first_step = min(_start_s(e) for e in step_track)
    last_step = max(_end_s(e) for e in step_track)
    # Steady-state step cadence: everything between first and last step
    # minus the one-off gaps (compiles, in-loop evals) that are attributed
    # to their own verdicts.  MFU reads this, not the raw window.
    active_s = max(
        (last_step - first_step)
        - sum(
            _dur_s(e)
            for name in ("compile_train_step", "eval")
            for e in vocab[name]
            if _start_s(e) >= first_step and _end_s(e) <= last_step
        ),
        1e-9,
    )
    return {
        "count": len(step_track),
        "window_s": _r(window_s),
        "active_train_s": _r(active_s),
        "steps_per_s": _r(len(step_track) / active_s),
        "decomposition": decomposition,
        "fractions_sum": _r(sum(decomposition.values())),
        "totals_s": {k: _r(v, 4) for k, v in attributed.items()},
    }


def _span_stats(spans: dict[str, list[dict]]) -> dict:
    out = {}
    for name in _SPAN_STAT_NAMES:
        group = spans.get(name)
        if not group:
            continue
        stats = latency_percentiles([_dur_s(e) * 1e3 for e in group])
        stats["total_s"] = _r(sum(_dur_s(e) for e in group), 4)
        out[name] = stats
    return out


def _overlap_section(
    spans: dict[str, list[dict]],
    dispatch_name: str,
    fetch_name: str,
    convert_name: str | None,
) -> dict | None:
    """One-behind pipeline efficiency from a dispatch/fetch span pair.

    With perfect overlap the host's ``fetch`` (device_get) barely blocks:
    the device finished batch N−1 while the host dispatched/converted
    batch N.  With no overlap the host spends its whole non-dispatch time
    blocked in fetch.  ``overlap_efficiency = 1 − Σfetch / wall`` maps
    those extremes to ~1 and ~0 on the pipeline's own wall clock.
    """
    dispatch = spans.get(dispatch_name) or []
    fetch = spans.get(fetch_name) or []
    if not dispatch or not fetch:
        return None
    wall_start = min(_start_s(e) for e in dispatch + fetch)
    wall_end = max(_end_s(e) for e in dispatch + fetch)
    wall_s = max(wall_end - wall_start, 1e-9)
    dispatch_s = sum(_dur_s(e) for e in dispatch)
    fetch_s = sum(_dur_s(e) for e in fetch)
    out = {
        "batches": len(dispatch),
        "wall_s": _r(wall_s),
        "dispatch_s": _r(dispatch_s, 4),
        "fetch_blocked_s": _r(fetch_s, 4),
        "overlap_efficiency": _r(min(1.0, max(0.0, 1.0 - fetch_s / wall_s))),
    }
    if convert_name:
        convert = spans.get(convert_name) or []
        if convert:
            convert_s = sum(_dur_s(e) for e in convert)
            # Host conversion that ran while the driver stream was still
            # in flight (the consumer-thread overlap the pipelined eval
            # exists for).
            overlapped = sum(
                max(
                    0.0,
                    min(_end_s(e), wall_end) - max(_start_s(e), wall_start),
                )
                for e in convert
            )
            out["convert_s"] = _r(convert_s, 4)
            out["convert_overlap"] = _r(
                min(1.0, overlapped / max(convert_s, 1e-9))
            )
    return out


def _queue_section(
    counters: dict[str, list[tuple[float, float]]],
    data_wait_spans: list[dict],
) -> dict:
    out: dict[str, dict] = {}
    for name, series in sorted(counters.items()):
        if _is_memory_gauge(name):
            continue
        values = [v for _, v in series]
        out[name] = {
            "samples": len(values),
            "mean": _r(sum(values) / len(values), 3),
            "min": _r(min(values), 3),
            "max": _r(max(values), 3),
            "zero_fraction": _r(
                sum(1 for v in values if v == 0) / len(values)
            ),
        }
    feed = counters.get(_FEED_QUEUE)
    if feed and data_wait_spans:
        # Cross-reference: at each data_wait span's start, what depth did
        # the feed queue last report?  Time-weighted by span duration so
        # one long starvation outweighs many micro-waits.
        starved = 0.0
        total = 0.0
        times = [t for t, _ in feed]
        for e in data_wait_spans:
            t0 = _start_s(e)
            depth = None
            lo, hi = 0, len(times)
            while lo < hi:  # rightmost sample at/before t0
                mid = (lo + hi) // 2
                if times[mid] <= t0:
                    lo = mid + 1
                else:
                    hi = mid
            if lo > 0:
                depth = feed[lo - 1][1]
            total += _dur_s(e)
            if depth is not None and depth == 0:
                starved += _dur_s(e)
        if total > 0:
            out.setdefault(_FEED_QUEUE, {})["starved_data_wait_fraction"] = _r(
                starved / total
            )
    return out


def _is_memory_gauge(name: str) -> bool:
    return name.startswith("dev") and name.endswith(
        ("bytes_in_use", "peak_bytes_in_use")
    )


def _memory_section(counters: dict[str, list[tuple[float, float]]]) -> dict:
    gauges = {n: s for n, s in counters.items() if _is_memory_gauge(n)}
    if not gauges:
        return {"available": False}
    out: dict[str, Any] = {"available": True, "gauges": {}}
    for name, series in sorted(gauges.items()):
        (t0, v0), (t1, v1) = series[0], series[-1]
        g = {
            "samples": len(series),
            "first_bytes": _r(v0, 0),
            "last_bytes": _r(v1, 0),
            "peak_bytes": _r(max(v for _, v in series), 0),
        }
        if t1 > t0:
            g["trend_bytes_per_s"] = _r((v1 - v0) / (t1 - t0), 1)
        out["gauges"][name] = g
    return out


def _load_runs(
    events_path: str | None,
) -> tuple[list[dict] | None, str | None]:
    """ONE ``split_runs`` parse of metrics.jsonl, shared by the events,
    violations and numerics sections (a long run's JSONL is multi-MB —
    three per-section parses were pure waste).  Returns (runs, error)."""
    if not events_path or not os.path.exists(events_path):
        return None, None
    try:
        return split_runs(events_path), None
    except OSError as e:
        return None, repr(e)[:200]


def _events_section(
    runs: list[dict] | None, error: str | None = None
) -> dict:
    if error:
        return {"available": False, "error": error}
    if not runs:
        return {"available": False}
    run = runs[-1]  # the most recent run in an append-mode file
    header = run.get("header") or {}
    records = run.get("records") or []
    compiles = [r for r in records if r.get("event") == "compile"]
    stalls = [r for r in records if r.get("event") == "watchdog_stall"]
    dropped = sum(len(r.get("dropped_metrics") or []) for r in records)
    return {
        "available": True,
        "runs_in_file": len(runs),
        "corrupt_lines": sum(len(r.get("corrupt") or []) for r in runs),
        "header": {
            k: header.get(k)
            for k in (
                "run_id",
                "device_kind",
                "local_device_count",
                "process_count",
                "config_digest",
            )
        },
        "compile": {
            "count": len(compiles),
            "build_s_total": _r(
                sum(float(r.get("build_s") or 0.0) for r in compiles), 3
            ),
        },
        "watchdog_stalls": len(stalls),
        "dropped_metrics": dropped,
    }


def _mfu_section(
    events: list[dict], steps: dict | None, device_kind: str | None
) -> dict:
    cost = [
        e
        for e in _instants(events, "cost_analysis")
        if (e.get("args") or {}).get("target") == "train_step"
    ]
    flops_vals = [
        float((e.get("args") or {}).get("flops") or 0.0) for e in cost
    ]
    flops_vals = [v for v in flops_vals if v > 0]
    batches = [
        int((e.get("args") or {}).get("batch") or 0) for e in cost
    ]
    batches = [b for b in batches if b > 0]
    peak, peak_source = device_peak_tflops(device_kind)
    out: dict[str, Any] = {
        "flops_per_step": _r(
            sum(flops_vals) / len(flops_vals), 1
        )
        if flops_vals
        else None,
        "flops_source": "trace_cost_analysis" if flops_vals else None,
        "steps_per_s": steps.get("steps_per_s") if steps else None,
        "images_per_s": None,
        "achieved_tflops": None,
        "peak_tflops": peak,
        "peak_source": peak_source,
        "mfu": None,
    }
    if flops_vals and steps and steps.get("steps_per_s"):
        achieved = (
            (sum(flops_vals) / len(flops_vals)) * steps["steps_per_s"] / 1e12
        )
        out["achieved_tflops"] = _r(achieved)
        if batches:
            out["images_per_s"] = _r(
                steps["steps_per_s"] * sum(batches) / len(batches), 3
            )
        if peak:
            out["mfu"] = _r(achieved / peak)
    if peak_source == "nominal-cpu":
        out["note"] = (
            "peak is a nominal CPU figure; mfu is order-of-magnitude only"
        )
    return out


def _violations_section(
    events: list[dict], runs: list[dict] | None
) -> dict:
    """The SLO read-back: ``slo_violation`` trace instants + JSONL events
    aggregated per rule.  The JSONL records are the richer source (they
    carry the description); the trace markers stand in when a run had no
    events half — per-rule aggregates prefer whichever source saw more
    of that rule (the monitor emits to both, so counts normally agree).
    """
    trace_v = [
        dict(e.get("args") or {}) for e in _instants(events, "slo_violation")
    ]
    jsonl_v: list[dict] = []
    if runs:
        jsonl_v = [
            r
            for r in runs[-1].get("records", [])
            if r.get("event") == "slo_violation"
        ]
    rules: dict[str, dict] = {}
    for source in (jsonl_v, trace_v):
        counts: dict[str, int] = {}
        for v in source:
            name = str(v.get("rule") or "?")
            counts[name] = counts.get(name, 0) + 1
            agg = rules.setdefault(
                name,
                {
                    "count": 0,
                    "metric": v.get("metric"),
                    "op": v.get("op"),
                    "max_sustained_s": 0.0,
                    "last_value": None,
                    "threshold": None,
                    "description": v.get("description"),
                },
            )
            agg["max_sustained_s"] = max(
                agg["max_sustained_s"], float(v.get("sustained_s") or 0.0)
            )
            agg["last_value"] = v.get("value")
            agg["threshold"] = v.get("threshold")
            if v.get("description"):
                agg["description"] = v.get("description")
        for name, n in counts.items():
            rules[name]["count"] = max(rules[name]["count"], n)
    out = {
        "trace_markers": len(trace_v),
        "jsonl_events": len(jsonl_v),
        "rules": {k: rules[k] for k in sorted(rules)},
    }
    # Self-healing resumes (ISSUE 11): an auto_resume is a survived
    # incident, not a violation, but it belongs in the same read-back —
    # a report whose run silently restarted mid-way must say so.  The
    # key is present only when such events exist, so healthy-run reports
    # (and the committed goldens) are byte-identical to schema v3.
    if runs:
        resumes = [
            r
            for r in runs[-1].get("records", [])
            if r.get("event") == "auto_resume"
        ]
        if resumes:
            out["auto_resumes"] = {
                "count": len(resumes),
                "restored_steps": [
                    r.get("restored_step") for r in resumes
                ],
                "excluded_ids": sorted(
                    {
                        int(i)
                        for r in resumes
                        for i in (r.get("exclude_ids") or [])
                    }
                ),
            }
    return out


def _series_stats(values: list[float]) -> dict | None:
    finite = [v for v in values if isinstance(v, (int, float))]
    if not finite:
        return None
    fin = [v for v in finite if math.isfinite(v)]
    out = {
        "samples": len(finite),
        "nonfinite_samples": len(finite) - len(fin),
        "last": _r(finite[-1]) if math.isfinite(finite[-1]) else None,
    }
    if fin:
        out["max"] = _r(max(fin))
        out["min"] = _r(min(fin))
        s = sorted(fin)
        mid = len(s) // 2
        out["median"] = _r(
            s[mid] if len(s) % 2 else (s[mid - 1] + s[mid]) / 2.0
        )
    return out


def _numerics_section(
    events: list[dict], runs: list[dict] | None, dump_path: str | None
) -> dict:
    """The numerics flight recorder's read-back (ISSUE 10): ``numerics``
    JSONL records (per-log-window grad/update health), ``numerics_trip``
    markers from BOTH the trace timeline and the JSONL, and the
    NUMERICS_DUMP.json the abort path landed (cross-referenced, never
    re-derived).  ``available`` is False only when no source exists at
    all — a run with the summary off but a tripped finite-check still
    gets its trip + dump surfaced."""
    def safe(v):
        # NaN/Inf values (a trip's whole point) must not leak bare NaN
        # tokens into the report JSON — stringify them.
        if isinstance(v, float) and not math.isfinite(v):
            return repr(v)
        return v

    records: list[dict] = []
    trips_jsonl: list[dict] = []
    metric_grad_norms: list[float] = []
    if runs:
        for r in runs[-1].get("records", []):
            if r.get("event") == "numerics":
                records.append(r)
            elif r.get("event") == "numerics_trip":
                trips_jsonl.append(r)
            elif "step" in r and "event" not in r:
                if isinstance(r.get("train/grad_norm"), (int, float)):
                    metric_grad_norms.append(r["train/grad_norm"])
    trips_trace = [
        dict(e.get("args") or {})
        for e in _instants(events, "numerics_trip")
    ]
    # The richer JSONL trips win; trace markers stand in for a run whose
    # events half is missing (the violations-section policy).
    trips = trips_jsonl or trips_trace
    dump = None
    if dump_path and os.path.exists(dump_path):
        try:
            with open(dump_path) as f:
                d = json.load(f)
            tripped = d.get("tripped")
            dump = {
                "present": True,
                "step": d.get("step"),
                "first_nonfinite": d.get("first_nonfinite"),
                "tripped": {k: safe(v) for k, v in tripped.items()}
                if isinstance(tripped, dict)
                else tripped,
            }
        except (OSError, ValueError) as e:
            dump = {"present": True, "error": repr(e)[:200]}
    grad_norms = [
        r["grad_norm"]
        for r in records
        if isinstance(r.get("grad_norm"), (int, float))
    ] or metric_grad_norms
    nonfinite_total = sum(
        float(r.get("nonfinite_grads") or 0.0)
        for r in records
        if isinstance(r.get("nonfinite_grads"), (int, float))
    )
    out: dict[str, Any] = {
        "available": bool(records or trips or dump or metric_grad_norms),
        "records": len(records),
        "grad_norm": _series_stats(grad_norms),
        "update_ratio": _series_stats(
            [
                r["update_ratio"]
                for r in records
                if isinstance(r.get("update_ratio"), (int, float))
            ]
        ),
        "replica_agreement": _series_stats(
            [
                r["replica_agreement"]
                for r in records
                if isinstance(r.get("replica_agreement"), (int, float))
            ]
        ),
        "nonfinite_total": _r(nonfinite_total, 1),
        "trips": {
            "count": max(len(trips_jsonl), len(trips_trace)),
            "trace_markers": len(trips_trace),
            "jsonl_events": len(trips_jsonl),
            "first": {
                k: safe(trips[0].get(k)) for k in ("metric", "step", "value")
            }
            if trips
            else None,
        },
        "dump": dump or {"present": False},
    }
    return out


def _stalls_section(events: list[dict], events_section: dict) -> dict:
    markers = _instants(events, "stall")
    components: dict[str, int] = {}
    for e in markers:
        c = str((e.get("args") or {}).get("component") or "?")
        components[c] = components.get(c, 0) + 1
    return {
        "trace_markers": len(markers),
        "jsonl_diagnoses": events_section.get("watchdog_stalls", 0)
        if events_section.get("available")
        else 0,
        "components": {k: components[k] for k in sorted(components)},
    }


# Bottleneck → the tune/ problems that attack it (tune CLI --from-report
# consumes these names directly: python -m ...tune --from-report).
_TUNE_OPS = {
    "device_step": ["focal", "matching", "nms"],
    "eval_pipeline": ["nms", "batch"],
    "eval_fetch_blocking": ["nms", "batch"],
    "serve_fetch_blocking": ["nms", "batch"],
    "host_input_pipeline": ["batch"],
}


def _slo_tune_ops(metric: str | None) -> list[str]:
    """Breached metric → the tune/ problems that attack it, so an SLO
    verdict at rank 1 still gives ``tune --from-report`` something to
    search (a stall/shed rule maps to nothing — those are capacity or
    wedge problems, not kernel-schedule problems)."""
    m = (metric or "").lower()
    if "latency" in m or "p99" in m or "p50" in m:
        return ["nms", "batch"]
    if "step_time" in m or "images_per_sec" in m:
        return ["focal", "matching", "nms"]
    if "data_wait" in m:
        return ["batch"]
    return []


def _bottlenecks(
    steps: dict | None,
    pipeline: dict,
    spans: dict[str, list[dict]],
    queues: dict,
    violations: dict | None = None,
    numerics: dict | None = None,
) -> list[dict]:
    """Ranked verdicts, scores all expressed as fractions of the main
    window so they are mutually comparable.  Non-empty whenever the trace
    carries any span at all (the generic fallback ranks raw span
    families when the train vocabulary is absent — bench traces).

    SLO violations outrank everything inferred: a breach of a DECLARED
    objective is evidence by fiat, so each violated rule contributes a
    ``slo:<rule>`` verdict at score 1.0 (inferred scores are window
    fractions ≤ 1) ON TOP of the top-3 inferred verdicts — the inferred
    ranking is never starved out of the report by a noisy SLO."""
    cands: list[dict] = []
    if steps is not None:
        d = steps["decomposition"]
        window_s = steps["window_s"]
        starved = (queues.get(_FEED_QUEUE) or {}).get(
            "starved_data_wait_fraction"
        )
        cands.append(
            {
                "name": "host_input_pipeline",
                "score": d["data_wait"],
                "spans": [
                    "data_wait",
                    "device-prefetch",
                    "pipe_decode_wait",
                    "shm_head_wait",
                    "decode",
                ],
                "evidence": (
                    f"host blocked on input {d['data_wait']:.1%} of the "
                    f"window"
                    + (
                        f"; feed queue empty for {starved:.1%} of that"
                        if starved is not None
                        else ""
                    )
                ),
                "suggestion": (
                    "raise --data-worker-procs/--workers (RUNBOOK 'Feeding "
                    "the chips'); starved feed queue = decode-bound host"
                ),
            }
        )
        cands.append(
            {
                "name": "compilation",
                "score": d["compile"],
                "spans": ["compile_train_step", "build_detect_fn"],
                "evidence": f"compiles took {d['compile']:.1%} of the window",
                "suggestion": (
                    "one-time cost on long runs; persistent compile cache / "
                    "AOT warmup if it dominates short ones"
                ),
            }
        )
        cands.append(
            {
                "name": "device_step",
                "score": d["step"],
                "spans": ["step"],
                "evidence": f"device step {d['step']:.1%} of the window",
                "suggestion": (
                    "the roofline lever: fused Pallas kernels for focal/"
                    "matching/NMS + a tune/ search on this device_kind"
                ),
            }
        )
        cands.append(
            {
                "name": "eval_pipeline",
                "score": d["eval"],
                "spans": ["eval", "final_eval", "detect_dispatch"],
                "evidence": f"in-loop eval {d['eval']:.1%} of the window",
                "suggestion": (
                    "--async-eval overlaps eval with the step stream; "
                    "tune/ batch axis raises detect throughput"
                ),
            }
        )
        cands.append(
            {
                "name": "logging_fetch",
                "score": d["metrics_fetch"],
                "spans": ["metrics_fetch"],
                "evidence": (
                    f"metric device_get {d['metrics_fetch']:.1%} of the "
                    "window"
                ),
                "suggestion": "raise --log-every",
            }
        )
    # Pipeline fetch-blocking verdicts exist with or WITHOUT a train loop
    # (a bench eval/serve trace has no `step` spans, but its fetch
    # blocking IS the detect-ceiling evidence tune/ exists to attack):
    # normalized by the loop window when one exists, else by the
    # pipeline's own wall.
    for key, name, span_list, suggestion in (
        (
            "eval",
            "eval_fetch_blocking",
            ["detect_fetch", "eval_put_wait"],
            "one-behind overlap is losing to device NMS time: tune/ nms "
            "+ per-bucket batch",
        ),
        (
            "serve",
            "serve_fetch_blocking",
            ["serve_fetch"],
            "tune/ nms + serve batch sizes",
        ),
    ):
        sec = pipeline.get(key)
        if sec is None:
            continue
        denom = (
            steps["window_s"] if steps is not None else sec["wall_s"]
        )
        if not denom:
            continue
        cands.append(
            {
                "name": name,
                "score": _r(min(1.0, sec["fetch_blocked_s"] / denom)),
                "spans": span_list,
                "evidence": (
                    f"{key} fetch blocked {sec['fetch_blocked_s']:.3f}s "
                    f"(overlap_efficiency "
                    f"{sec['overlap_efficiency']:.3f})"
                ),
                "suggestion": suggestion,
            }
        )
    if steps is None:
        # No train loop in this trace (bench/serve/tune artifacts): also
        # rank raw span families by their share of the span-covered
        # wall, skipping families a pipeline verdict already claims.
        claimed = {s for c in cands for s in c["spans"]}
        all_spans = [e for group in spans.values() for e in group]
        if all_spans:
            wall = max(_end_s(e) for e in all_spans) - min(
                _start_s(e) for e in all_spans
            )
            wall = max(wall, 1e-9)
            for name in sorted(spans):
                if name in claimed:
                    continue
                total = sum(_dur_s(e) for e in spans[name])
                cands.append(
                    {
                        "name": f"span:{name}",
                        "score": _r(min(1.0, total / wall)),
                        "spans": [name],
                        "evidence": f"{total:.3f}s across "
                        f"{len(spans[name])} spans",
                        "suggestion": "inspect this track in Perfetto",
                    }
                )
    cands = [c for c in cands if (c["score"] or 0) > 0]
    cands.sort(key=lambda c: (-c["score"], c["name"]))
    top = cands[:3]
    for c in top:
        c["tune_ops"] = _TUNE_OPS.get(c["name"], [])
    vio_cands: list[dict] = []
    for name, info in sorted(
        ((violations or {}).get("rules") or {}).items()
    ):
        vio_cands.append(
            {
                "name": f"slo:{name}",
                "score": 1.0,
                "spans": ["slo_violation"],
                "evidence": (
                    f"SLO {name!r} violated {info['count']}x "
                    f"({info.get('metric')} {info.get('op') or '>'} "
                    f"{info.get('threshold')}, last value "
                    f"{info.get('last_value')}, sustained "
                    f"{info.get('max_sustained_s')}s)"
                ),
                "suggestion": (
                    "a violated declared objective outranks inferred "
                    "bottlenecks: attack the breached metric first "
                    "(RUNBOOK 'Live telemetry')"
                ),
                "tune_ops": _slo_tune_ops(info.get("metric")),
            }
        )
    num_cands: list[dict] = []
    trips = ((numerics or {}).get("trips") or {}).get("count", 0)
    nonfinite = (numerics or {}).get("nonfinite_total") or 0
    if trips or nonfinite:
        # Numerical divergence outranks EVERYTHING — a run computing NaNs
        # has no performance question left to answer, so the verdict sits
        # above even declared-SLO breaches (which include the nonfinite
        # rule itself; acceptance pins rank 1 on the NaN smoke).
        first = ((numerics or {}).get("trips") or {}).get("first") or {}
        dump = (numerics or {}).get("dump") or {}
        located = (
            f"; first non-finite: {dump.get('first_nonfinite')}"
            if dump.get("first_nonfinite")
            else ""
        )
        num_cands.append(
            {
                "name": "numerics:divergence",
                "score": 1.0,
                "spans": ["numerics_trip"],
                "evidence": (
                    f"{int(trips)} tripped finite-check(s), "
                    f"{nonfinite:g} non-finite gradient element(s)"
                    + (
                        f" (tripped metric {first.get('metric')} at step "
                        f"{first.get('step')})"
                        if first.get("metric")
                        else ""
                    )
                    + located
                ),
                "suggestion": (
                    "read NUMERICS_DUMP.json (debug.py nans <dump>) for "
                    "the first non-finite layer/loss term — no "
                    "--debug-nans rerun needed (RUNBOOK 'Numerics "
                    "triage')"
                ),
                "tune_ops": [],
            }
        )
    top = num_cands + vio_cands + top
    for i, c in enumerate(top):
        c["rank"] = i + 1
    return top


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------


def analyze_events(
    events: list[dict],
    events_path: str | None = None,
    trace_health: dict | None = None,
    dump_path: str | None = None,
) -> dict:
    """Chrome events (+ optional events JSONL path + optional
    NUMERICS_DUMP.json path) → the report dict."""
    spans = _spans_by_name(events)
    counters = _counters_by_name(events)
    steps = _steps_section(spans)
    pipeline = {
        "eval": _overlap_section(
            spans, "detect_dispatch", "detect_fetch", "eval_convert"
        ),
        "serve": _overlap_section(
            spans, "serve_dispatch", "serve_fetch", "serve_convert"
        ),
    }
    queues = _queue_section(counters, spans.get("data_wait") or [])
    runs, runs_error = _load_runs(events_path)
    events_section = _events_section(runs, runs_error)
    violations = _violations_section(events, runs)
    numerics = _numerics_section(events, runs, dump_path)
    run_meta = _instants(events, "run_meta")
    meta_args = (run_meta[-1].get("args") or {}) if run_meta else {}
    device_kind = meta_args.get("device_kind") or (
        events_section.get("header", {}).get("device_kind")
        if events_section.get("available")
        else None
    )
    report = {
        "schema_version": SCHEMA_VERSION,
        "source": {
            "device_kind": device_kind,
            "local_device_count": meta_args.get("local_device_count"),
            "process_count": meta_args.get("process_count"),
            "events": bool(events_section.get("available")),
            "trace_events": len(events),
        },
        "steps": steps,
        "pipeline": pipeline,
        "queues": queues,
        "memory": _memory_section(counters),
        "mfu": _mfu_section(events, steps, device_kind),
        "stalls": _stalls_section(events, events_section),
        "violations": violations,
        "numerics": numerics,
        "events": events_section,
        "span_stats": _span_stats(spans),
        "bottlenecks": _bottlenecks(
            steps, pipeline, spans, queues, violations, numerics
        ),
        "health": dict(trace_health or {}),
    }
    return report


def _load_dir_inputs(
    obs_dir: str,
    trace_name: str,
    events_name: str | None,
    dump_name: str | None,
) -> tuple[list[dict], dict, str | None, str | None]:
    """The ONE obs-dir artifact resolver shared by ``analyze_dir`` and
    ``analyze_fleet_dir`` (a divergence here would silently fork plain
    and --fleet reports): (events, trace_health, events_path|None,
    dump_path|None), optional inputs resolved to None when absent."""
    events, health = load_trace(os.path.join(obs_dir, trace_name))
    events_path = (
        os.path.join(obs_dir, events_name) if events_name else None
    )
    if events_path and not os.path.exists(events_path):
        events_path = None
    dump_path = os.path.join(obs_dir, dump_name) if dump_name else None
    if dump_path and not os.path.exists(dump_path):
        dump_path = None
    return events, health, events_path, dump_path


def analyze_dir(
    obs_dir: str,
    trace_name: str = "trace.json",
    events_name: str | None = "metrics.jsonl",
    dump_name: str | None = "NUMERICS_DUMP.json",
) -> dict:
    """The offline entrypoint: an obs dir (as left by a --obs-trace run)
    → the report dict.  The trace is required; the events JSONL is
    enrichment (MFU falls back to trace instants, run metadata degrades
    to None).  ``events_name=None`` skips the JSONL entirely — the bench
    emitters use this: bench never writes events, and a shared obs dir
    may hold a PREVIOUS train run's metrics.jsonl whose header/compile
    records must not be attributed to this trace.  A NUMERICS_DUMP.json
    next to the trace (the loop's abort-path artifact) is
    cross-referenced into the numerics section when present."""
    events, health, events_path, dump_path = _load_dir_inputs(
        obs_dir, trace_name, events_name, dump_name
    )
    report = analyze_events(
        events,
        events_path=events_path,
        trace_health=health,
        dump_path=dump_path,
    )
    report["source"]["trace"] = trace_name
    return report


# ---------------------------------------------------------------------------
# Fleet mode (ISSUE 15): the merged multi-replica trace + federated metrics
# ---------------------------------------------------------------------------

# The fleet state transitions cross-referenced onto the report timeline —
# every one of these is emitted as BOTH a sink event and a trace instant
# carrying replica_id (serve/fleet.py), so the list is closed by design.
_FLEET_EVENT_NAMES = (
    "fleet_breaker_open",
    "fleet_breaker_half_open",
    "fleet_breaker_close",
    "fleet_redispatch",
    "canary_started",
    "canary_rollback",
    "canary_promoted",
    "fleet_replica_spawned",
    "fleet_replica_died",
    "fleet_replica_respawned",
    "fleet_respawn_failed",
    # Autoscaling control plane (ISSUE 19).
    "autoscaler_armed",
    "autoscale_decision",
    "autoscale_launch_failed",
    "respawn_budget_exhausted",
    "fleet_replica_joined",
    "fleet_replica_draining",
    "fleet_replica_removed",
)

# Serve stage-span families attributed per replica process track.
_FLEET_STAGE_NAMES = (
    "serve_preprocess",
    "serve_assemble",
    "serve_dispatch",
    "serve_fetch",
    "serve_convert",
)

_FLEET_TIMELINE_CAP = 500


def _process_labels(events: Iterable[dict]) -> dict[Any, str]:
    """pid → process label from the ``process_name`` metadata events
    (``p<idx>:<label> (pid N)`` as obs/trace.py writes them)."""
    out: dict[Any, str] = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            label = str((e.get("args") or {}).get("name") or "")
            if " (pid " in label:
                label = label.split(" (pid ", 1)[0]
            if ":" in label:
                label = label.split(":", 1)[1]
            out[e.get("pid")] = label
    return out


def _fed_replica_metrics(metrics_doc: dict | None) -> dict[str, dict]:
    """FLEET_METRICS.json → per-replica {completed, shed, p99_ms} from
    the federated sample lists (serve/fleet.py ``dump_federated``)."""
    out: dict[str, dict] = {}
    for rid, rec in sorted(((metrics_doc or {}).get("replicas") or {}).items()):
        completed = shed = 0.0
        p99 = None
        for name, labels, value in rec.get("samples") or []:
            if name == "serve_requests_completed_total":
                completed += float(value)
            elif name == "serve_shed_total":
                shed += float(value)
            elif (
                name == "serve_request_latency_ms"
                and (labels or {}).get("quantile") == "0.99"
            ):
                p99 = float(value)
        out[rid] = {
            "completed": _r(completed, 1),
            "shed": _r(shed, 1),
            "p99_ms": _r(p99, 3),
        }
    return out


def _fleet_section(
    events: list[dict], metrics_doc: dict | None
) -> dict:
    """Per-replica decomposition + routing attribution + the fleet event
    timeline — the read-back of a multi-replica run (ISSUE 15)."""
    spans = _spans_by_name(events)
    labels = _process_labels(events)
    reqs = spans.get("serve_request") or []

    by_replica: dict[str, list[dict]] = {}
    replica_pids: dict[str, set] = {}
    traces_by_replica: dict[str, set] = {}
    for e in reqs:
        args = e.get("args") or {}
        rid = str(
            args.get("replica") or labels.get(e.get("pid")) or "?"
        )
        by_replica.setdefault(rid, []).append(e)
        replica_pids.setdefault(rid, set()).add(e.get("pid"))
        if args.get("trace"):
            traces_by_replica.setdefault(rid, set()).add(
                str(args["trace"])
            )
    # A trace id whose spans landed on MORE THAN ONE replica is a
    # re-dispatched (or shed-then-retried) request — the cross-track
    # follow the tracing tentpole exists for.
    trace_owners: dict[str, set] = {}
    for rid, ids in traces_by_replica.items():
        for t in ids:
            trace_owners.setdefault(t, set()).add(rid)
    redispatched = sorted(
        t for t, owners in trace_owners.items() if len(owners) > 1
    )

    fed = _fed_replica_metrics(metrics_doc)
    busy = {
        rid: sum(_dur_s(e) for e in group)
        for rid, group in by_replica.items()
    }
    busy_total = sum(busy.values())
    # Stage spans carry no replica arg, only a pid: attribute a pid's
    # stage time to a replica ONLY when that pid hosts exactly one
    # replica (subprocess fleets).  An in-process LocalReplica fleet
    # shares one pid across replicas — crediting each with the shared
    # total would overcount N×, so those stages are skipped and flagged.
    pid_owners: dict[Any, set] = {}
    for rid, pids in replica_pids.items():
        for pid in pids:
            pid_owners.setdefault(pid, set()).add(rid)
    replicas: dict[str, dict] = {}
    for rid in sorted(set(by_replica) | set(fed)):
        group = by_replica.get(rid) or []
        entry: dict[str, Any] = {
            "requests": len(group),
            "busy_s": _r(busy.get(rid, 0.0), 4),
            # Time-weighted routing-share attribution: this replica's
            # share of all serve_request span time across the fleet.
            "routing_share": _r(
                busy.get(rid, 0.0) / busy_total if busy_total else 0.0
            ),
            "distinct_traces": len(traces_by_replica.get(rid) or ()),
        }
        if group:
            entry["latency"] = latency_percentiles(
                [_dur_s(e) * 1e3 for e in group]
            )
        all_pids = replica_pids.get(rid) or set()
        pids = {p for p in all_pids if len(pid_owners.get(p) or ()) == 1}
        if all_pids - pids:
            entry["stages_shared_process"] = True
        stages = {}
        for name in _FLEET_STAGE_NAMES:
            total = sum(
                _dur_s(e)
                for e in spans.get(name) or []
                if e.get("pid") in pids
            )
            if total:
                stages[name] = _r(total, 4)
        if stages:
            entry["stages_s"] = stages
        if rid in fed:
            entry["federated"] = fed[rid]
        replicas[rid] = entry

    timeline: list[dict] = []
    event_counts: dict[str, dict[str, int]] = {}
    for name in _FLEET_EVENT_NAMES:
        for e in _instants(events, name):
            args = e.get("args") or {}
            rid = str(args.get("replica_id") or "?")
            event_counts.setdefault(rid, {})
            event_counts[rid][name] = event_counts[rid].get(name, 0) + 1
            item = {"t_s": _r(_start_s(e), 3), "event": name}
            for k in (
                "replica_id", "reason", "trace", "rc", "rule",
                "decision", "delta",
            ):
                if args.get(k) is not None:
                    item[k] = args[k]
            timeline.append(item)
    timeline.sort(key=lambda x: (x["t_s"] or 0.0, x["event"]))
    truncated = max(0, len(timeline) - _FLEET_TIMELINE_CAP)
    return {
        "available": bool(reqs or timeline or fed),
        "replicas": replicas,
        "events_by_replica": {
            k: dict(sorted(v.items())) for k, v in sorted(event_counts.items())
        },
        "redispatched_traces": {
            "count": len(redispatched),
            "sample": redispatched[:10],
        },
        # The tail is what a post-mortem reads (the ring-buffer policy).
        "timeline": timeline[-_FLEET_TIMELINE_CAP:],
        "timeline_truncated": truncated,
    }


def _fleet_bottlenecks(fleet: dict) -> list[dict]:
    """Fleet verdicts, same shape as every other bottleneck entry so the
    schema-v3 machinery (``tune --from-report``, the checks) consumes
    them unchanged: the UNAVAILABLE replica first (a lost replica has no
    performance question left at fleet scope), then the most-shed and
    the slowest replica."""
    cands: list[dict] = []
    counts = fleet.get("events_by_replica") or {}
    death_score: dict[str, int] = {}
    for rid, evs in counts.items():
        if rid == "?":
            continue
        score = 2 * evs.get("fleet_replica_died", 0) + evs.get(
            "fleet_breaker_open", 0
        )
        if score:
            death_score[rid] = score
    if death_score:
        rid = max(sorted(death_score), key=lambda r: death_score[r])
        evs = counts[rid]
        cands.append(
            {
                "name": f"fleet:unavailable_replica:{rid}",
                "score": 1.0,
                "spans": ["fleet_breaker_open", "fleet_redispatch"],
                "evidence": (
                    f"replica {rid!r}: "
                    f"{evs.get('fleet_replica_died', 0)} death(s), "
                    f"{evs.get('fleet_breaker_open', 0)} breaker "
                    f"open(s), "
                    f"{evs.get('fleet_replica_respawned', 0)} respawn(s)"
                ),
                "suggestion": (
                    "follow this replica's track in the merged trace "
                    "around the breaker-open instants; the re-dispatch "
                    "markers carry the affected trace ids"
                ),
                "tune_ops": [],
            }
        )
    # Underprovisioned fleet (ISSUE 19): scale-up breaches the policy
    # could NOT act on because the fleet was already at max_replicas —
    # the capped autoscale_decision instants are the evidence trail.
    decisions = [
        it for it in fleet.get("timeline") or []
        if it.get("event") == "autoscale_decision"
    ]
    capped = [
        it for it in decisions if it.get("decision") == "scale_up_capped"
    ]
    if capped:
        ups = sum(
            1 for it in decisions if it.get("decision") == "scale_up"
        )
        reasons = sorted({str(it.get("reason")) for it in capped})
        cands.append(
            {
                "name": "fleet:underprovisioned",
                "score": _r(
                    min(1.0, len(capped) / max(1.0, len(capped) + ups))
                ),
                "spans": ["serve_request"],
                "evidence": (
                    f"{len(capped)} scale-up breach(es) "
                    f"({', '.join(reasons)}) blocked at max_replicas "
                    f"vs {ups} executed scale-up(s) — demand outgrew "
                    "the replica ceiling"
                ),
                "suggestion": (
                    "raise max_replicas (or per-replica slot capacity) "
                    "in the autoscale policy; each capped "
                    "autoscale_decision on the timeline carries the "
                    "breached signal values"
                ),
                "tune_ops": [],
            }
        )
    replicas = fleet.get("replicas") or {}
    sheds = {
        rid: float((r.get("federated") or {}).get("shed") or 0.0)
        for rid, r in replicas.items()
    }
    if any(sheds.values()):
        rid = max(sorted(sheds), key=lambda r: sheds[r])
        done = float(
            (replicas[rid].get("federated") or {}).get("completed") or 0.0
        )
        frac = sheds[rid] / max(1.0, sheds[rid] + done)
        cands.append(
            {
                "name": f"fleet:shed_replica:{rid}",
                "score": _r(min(1.0, frac)),
                "spans": ["serve_request"],
                "evidence": (
                    f"replica {rid!r} shed {sheds[rid]:g} requests "
                    f"({frac:.1%} of its traffic) — the fleet's worst"
                ),
                "suggestion": (
                    "raise this replica's queue bounds or lower its "
                    "routed share; a shedding replica under a healthy "
                    "fleet is a capacity mismatch, not a kernel problem"
                ),
                "tune_ops": [],
            }
        )
    p99s = {
        rid: float(
            (r.get("latency") or {}).get("p99_ms")
            or (r.get("federated") or {}).get("p99_ms")
            or 0.0
        )
        for rid, r in replicas.items()
    }
    p99s = {rid: v for rid, v in p99s.items() if v > 0}
    if len(p99s) > 1:
        rid = max(sorted(p99s), key=lambda r: p99s[r])
        rest = sorted(v for r, v in p99s.items() if r != rid)
        med = rest[len(rest) // 2]
        if med > 0 and p99s[rid] > med:
            cands.append(
                {
                    "name": f"fleet:slow_replica:{rid}",
                    "score": _r(
                        min(1.0, (p99s[rid] - med) / p99s[rid])
                    ),
                    "spans": ["serve_request", "serve_fetch"],
                    "evidence": (
                        f"replica {rid!r} p99 {p99s[rid]:.1f} ms vs "
                        f"{med:.1f} ms at the rest of the fleet"
                    ),
                    "suggestion": (
                        "compare this replica's serve stage spans "
                        "against a healthy track; tune/ nms + batch on "
                        "its device_kind if device-bound"
                    ),
                    "tune_ops": ["nms", "batch"],
                }
            )
    cands = [c for c in cands if (c["score"] or 0) > 0]
    cands.sort(key=lambda c: (-c["score"], c["name"]))
    return cands


def analyze_fleet_dir(
    obs_dir: str,
    trace_name: str = "trace.json",
    events_name: str | None = "metrics.jsonl",
    metrics_name: str | None = "FLEET_METRICS.json",
    dump_name: str | None = "NUMERICS_DUMP.json",
) -> dict:
    """``obs/analyze --fleet``: the standard report over the MERGED
    fleet trace, plus the ``fleet`` section (per-replica decomposition,
    time-weighted routing share, breaker/canary/re-dispatch timeline,
    federated metrics cross-reference) and fleet verdicts ranked into
    ``bottlenecks`` with the same schema-v3 machinery — below declared
    numerics/SLO breaches, above inferred single-process bottlenecks."""
    events, health, events_path, dump_path = _load_dir_inputs(
        obs_dir, trace_name, events_name, dump_name
    )
    report = analyze_events(
        events,
        events_path=events_path,
        trace_health=health,
        dump_path=dump_path,
    )
    metrics_doc = None
    metrics_path = (
        os.path.join(obs_dir, metrics_name) if metrics_name else None
    )
    if metrics_path and os.path.exists(metrics_path):
        try:
            with open(metrics_path) as f:
                metrics_doc = json.load(f)
        except (OSError, ValueError) as e:
            report["health"]["fleet_metrics_error"] = repr(e)[:200]
    fleet = _fleet_section(events, metrics_doc)
    report["fleet"] = fleet
    report["source"]["trace"] = trace_name
    report["source"]["fleet_metrics"] = bool(metrics_doc)
    def _is_head(b: dict) -> bool:
        return str(b.get("name", "")).startswith(("numerics:", "slo:"))

    heads = [b for b in report["bottlenecks"] if _is_head(b)]
    rest = [b for b in report["bottlenecks"] if not _is_head(b)]
    merged = heads + _fleet_bottlenecks(fleet) + rest
    for i, b in enumerate(merged):
        b["rank"] = i + 1
    report["bottlenecks"] = merged
    return report


def span_attribution(events: list[dict]) -> dict | None:
    """Compact attribution for an in-process event snapshot — the piece
    ``bench.py --trace`` folds into its committed JSON line so the
    BENCH_rNN trajectory carries data_wait%/overlap% history, not bare
    imgs/s.  None when there is nothing to attribute."""
    spans = _spans_by_name(events)
    all_spans = [e for group in spans.values() for e in group]
    if not all_spans:
        return None
    wall = max(_end_s(e) for e in all_spans) - min(
        _start_s(e) for e in all_spans
    )
    wall = max(wall, 1e-9)
    by_span = {
        name: _r(sum(_dur_s(e) for e in group), 4)
        for name, group in sorted(spans.items())
    }
    steps = _steps_section(spans)
    out: dict[str, Any] = {
        "wall_s": _r(wall, 3),
        "by_span_s": by_span,
        "decomposition": steps["decomposition"] if steps else None,
    }
    overlap = {}
    for key, names in (
        ("eval", ("detect_dispatch", "detect_fetch", "eval_convert")),
        ("serve", ("serve_dispatch", "serve_fetch", "serve_convert")),
    ):
        sec = _overlap_section(spans, *names)
        if sec is not None:
            overlap[key] = sec["overlap_efficiency"]
    out["overlap_efficiency"] = overlap or None
    return out


def write_report(report: dict, path: str) -> str:
    """Serialize deterministically (sorted keys, trailing newline) so the
    inline and offline emitters produce byte-identical files."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(report, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path


def validate_report(report: Any) -> list[str]:
    """Structural schema check → list of problems (empty = valid).  Used
    by the CLI, perf-report-check, and the fixture tests."""
    problems: list[str] = []
    if not isinstance(report, dict):
        return ["report is not an object"]
    if report.get("schema_version") != SCHEMA_VERSION:
        problems.append(
            f"schema_version {report.get('schema_version')!r} != "
            f"{SCHEMA_VERSION}"
        )
    for key in (
        "source",
        "steps",
        "pipeline",
        "queues",
        "memory",
        "mfu",
        "stalls",
        "violations",
        "numerics",
        "events",
        "span_stats",
        "bottlenecks",
        "health",
    ):
        if key not in report:
            problems.append(f"missing section {key!r}")
    violations = report.get("violations")
    if not isinstance(violations, dict) or not isinstance(
        violations.get("rules"), dict
    ):
        problems.append("violations section malformed (needs a rules map)")
    numerics = report.get("numerics")
    if not isinstance(numerics, dict) or "available" not in numerics or not (
        isinstance(numerics.get("trips"), dict)
    ):
        problems.append(
            "numerics section malformed (needs available + trips map)"
        )
    steps = report.get("steps")
    if isinstance(steps, dict):
        d = steps.get("decomposition")
        if not isinstance(d, dict) or set(d) != set(_DECOMP_KEYS):
            problems.append("steps.decomposition keys wrong")
        else:
            if any(
                not isinstance(v, (int, float)) or v < 0 or v > 1
                for v in d.values()
            ):
                problems.append("steps.decomposition fraction out of [0,1]")
            elif abs(sum(d.values()) - 1.0) > 0.02:
                problems.append(
                    f"steps.decomposition sums to {sum(d.values()):.4f}, "
                    "not ~1"
                )
    bn = report.get("bottlenecks")
    if not isinstance(bn, list):
        problems.append("bottlenecks is not a list")
    else:
        for i, b in enumerate(bn):
            if not isinstance(b, dict) or not {
                "rank",
                "name",
                "score",
                "spans",
            } <= set(b):
                problems.append(f"bottlenecks[{i}] malformed")
            elif b.get("rank") != i + 1:
                problems.append(f"bottlenecks[{i}] rank out of order")
    mfu = report.get("mfu")
    if isinstance(mfu, dict):
        missing = {"flops_per_step", "peak_tflops", "mfu"} - set(mfu)
        if missing:
            problems.append(f"mfu missing {sorted(missing)}")
    else:
        problems.append("mfu is not an object")
    return problems


def auto_emit(
    obs_dir: str,
    trace_name: str = "trace.json",
    out_name: str = "PERF_REPORT.json",
    sink: Any | None = None,
    events_name: str | None = "metrics.jsonl",
) -> str | None:
    """The finalize-path hook (train.py / bench.py): analyze + write the
    report next to the trace.  NEVER raises — a run that trained for
    hours must not die in its post-mortem; failure is ONE structured
    ``perf_report_error`` event (to ``sink`` when given, and stderr
    either way)."""
    try:
        report = analyze_dir(
            obs_dir, trace_name=trace_name, events_name=events_name
        )
        return write_report(report, os.path.join(obs_dir, out_name))
    except Exception as e:
        if sink is not None:
            try:
                sink.event(
                    "perf_report_error", obs_dir=obs_dir, error=repr(e)[:500]
                )
            except Exception:
                pass  # the stderr line below still lands
        print(
            json.dumps(
                {
                    "event": "perf_report_error",
                    "obs_dir": obs_dir,
                    "error": repr(e)[:500],
                }
            ),
            file=sys.stderr,
            flush=True,
        )
        return None
