"""Numerics flight recorder: in-step gradient/update health + NaN provenance.

The obs stack explains *where time went* (trace/analyze) and *how loaded
the process is* (telemetry/slo) — this module is the third axis: *is
training numerically healthy, right now, and if not, where did it break*.
Three coordinated pieces (ISSUE 10):

- **In-step summary** (jit-pure, fused into the compiled train step):
  global + per-layer-group gradient norms, the update/param-norm ratio,
  and a non-finite element count — computed from arrays the step already
  holds, ~2 extra global reduces when enabled and NOTHING when disabled
  (the gate is a trace-time Python bool: the disabled step's HLO is
  byte-identical to the pre-ISSUE-10 step).  The pre-clip global grad
  norm is computed ONCE and shared with the optax clip chain
  (train/optim.py ``clip_by_global_norm_precomputed`` consumes it via
  extra args) instead of being recomputed inside the clip.
- **Provenance pass** (host-side, failure path only): when the loop's
  finite-check trips, ``provenance`` localizes the first non-finite
  loss term / parameter / layer activation (one forward with flax
  ``capture_intermediates`` — no ``--debug-nans`` rerun) and
  ``write_dump`` lands ONE ``NUMERICS_DUMP.json`` (step, batch source
  ids, rng seed, per-layer stats) before the abort raises.
  ``debug.py nans`` is a thin driver over ``load_dump``/``format_dump``
  — the tree-walk lives here and only here.
- **Cross-replica agreement probe** (``replica_agreement``, called
  inside the sharded step): each replica's LOCAL pre-allreduce gradient
  norm vs the axis min/max — silent desync (one replica stepping on
  corrupted params) shows up as a collapsing agreement ratio long
  before the loss goes visibly wrong on the multichip/ZeRO path.

House rules: the in-step helpers are jit-pure by construction (pure
``jnp``, no clocks/prints/IO — the lint engine's jit-purity rule checks
them for free); the host helpers run only on the failure path or under
an explicit CLI, so their cost is irrelevant.  This module imports jax
and must stay OUT of jax-free processes — ``obs/__init__`` exposes it
lazily, like ``obs.telemetry``/``obs.slo``.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
from typing import Any, Mapping

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# ---------------------------------------------------------------------------
# Config + the metric-key vocabulary
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class NumericsConfig:
    """Compile-time gate for the in-step summary (train/step.py).

    ``enabled=False`` adds NOTHING to the compiled step (the gate is a
    Python bool at trace time); the loop's record sites then cost one
    bool check each, the telemetry-discipline contract."""

    enabled: bool = False
    per_group: bool = True  # per-top-level-param-group gradient norms
    replica_agreement: bool = True  # cross-replica probe (mesh steps only)


#: Metric keys the summary contributes (the loop/telemetry/analyzer read
#: these names — one vocabulary, defined here).
GRAD_NORM = "grad_norm"
UPDATE_RATIO = "update_ratio"
NONFINITE = "nonfinite_grads"
REPLICA_AGREEMENT = "replica_agreement"
GROUP_PREFIX = "gnorm/"

#: Comm-subsystem health keys (ISSUE 13, comm/compress.comm_metrics):
#: the error-feedback residual's global norm, the fraction of quantized
#: elements at the clip boundary (scale saturation — a spike means the
#: gradient distribution blew past the per-block scales), and the
#: plan's static bytes-on-wire.  Same vocabulary discipline as the
#: numerics keys: the step emits them, the loop's record site feeds the
#: telemetry gauges, the ef_residual_spike SLO rule watches the gauge.
EF_RESIDUAL = "ef_residual_norm"
EF_SATURATION = "ef_saturation"
COMM_BYTES = "comm_compressed_bytes"
#: Per-hop keys (ISSUE 16): present only on hierarchical-topology runs.
#: The ICI/DCN byte split is the static wire accounting per fabric; the
#: DCN-labeled residual norm makes a cross-slice EF blow-up attributable
#: (the per-hop ef_residual_spike rule watches its gauge).
EF_RESIDUAL_DCN = "ef_residual_norm_dcn"
COMM_ICI_BYTES = "comm_ici_bytes"
COMM_DCN_BYTES = "comm_dcn_bytes"

#: Scalars whose non-finiteness the provenance pass attributes first, in
#: root-cause order (a NaN cls_loss names the classification path even
#: though the total loss is NaN too).
_SCALAR_ORDER = (
    "cls_loss",
    "box_loss",
    "loss",
    GRAD_NORM,
    "param_norm",
    UPDATE_RATIO,
)

_EPS = 1e-16


def numerics_metric_keys(scalars: Mapping[str, Any]) -> list[str]:
    """The summary's keys present in a metrics mapping (loop record site)."""
    fixed = {GRAD_NORM, UPDATE_RATIO, NONFINITE, REPLICA_AGREEMENT}
    return sorted(
        k for k in scalars if k in fixed or k.startswith(GROUP_PREFIX)
    )


# ---------------------------------------------------------------------------
# jit-pure in-step helpers (train/step.py)
# ---------------------------------------------------------------------------


def nonfinite_count(tree: Any) -> jnp.ndarray:
    """Total non-finite elements across a pytree (one fused reduce)."""
    leaves = jax.tree.leaves(tree)
    if not leaves:
        return jnp.zeros((), jnp.float32)
    return sum(
        jnp.sum(~jnp.isfinite(leaf)).astype(jnp.float32) for leaf in leaves
    )


def group_norms(tree: Mapping[str, Any]) -> dict[str, jnp.ndarray]:
    """L2 norm per top-level subtree (``backbone``/``fpn``/``cls_head``/
    ``box_head`` for the RetinaNet family) — the per-layer-group view
    that tells a diverging head from a diverging backbone."""
    out: dict[str, jnp.ndarray] = {}
    for key in tree:
        sq = sum(
            jnp.sum(jnp.square(leaf)) for leaf in jax.tree.leaves(tree[key])
        )
        out[str(key)] = jnp.sqrt(sq)
    return out


def update_norm(params: Any, new_params: Any) -> jnp.ndarray:
    """Global L2 norm of the applied update (new − old), one reduce."""
    sq = sum(
        jnp.sum(jnp.square(n - o))
        for n, o in zip(jax.tree.leaves(new_params), jax.tree.leaves(params))
    )
    return jnp.sqrt(sq)


def update_ratio(
    params: Any, new_params: Any, param_norm: jnp.ndarray
) -> jnp.ndarray:
    """||new − old|| / ||new|| — the classic step-health ratio (a healthy
    run sits around 1e-3; ~1 means the update is rewriting the model,
    ~0 under a finite loss means the optimizer has stalled)."""
    return update_norm(params, new_params) / jnp.maximum(param_norm, _EPS)


def step_summary(
    grads: Any,
    params: Any,
    new_params: Any,
    param_norm: jnp.ndarray,
    config: NumericsConfig,
) -> dict[str, jnp.ndarray]:
    """The fused per-step numerics summary (call INSIDE the train step,
    after the gradient reduce and the update, on REPLICATED trees —
    the ZeRO step hand-assembles the same keys from its shards).
    Returns metric entries to merge into the step's metrics dict; ~2
    extra global reduces (non-finite count + update norm) plus one small
    reduce per group."""
    out: dict[str, jnp.ndarray] = {
        NONFINITE: nonfinite_count(grads),
        UPDATE_RATIO: update_ratio(params, new_params, param_norm),
    }
    if config.per_group and isinstance(grads, Mapping):
        for key, norm in group_norms(grads).items():
            out[f"{GROUP_PREFIX}{key}"] = norm
    return out


def replica_agreement(
    local_norm: jnp.ndarray, axis_name: str
) -> jnp.ndarray:
    """min/max ratio of the per-replica LOCAL gradient norms over a mesh
    axis (call inside ``shard_map``).  ~1.0 = replicas agree (healthy
    data variation keeps it well above 0); a collapsing ratio is the
    silent-desync signature — one replica's gradients have diverged from
    the rest without any collective erroring."""
    mx = lax.pmax(local_norm, axis_name)
    mn = lax.pmin(local_norm, axis_name)
    return jnp.where(mx > 0, mn / jnp.maximum(mx, _EPS), 1.0)


# ---------------------------------------------------------------------------
# Host-side finite checks (train/loop.py — cadence + pre-save share these)
# ---------------------------------------------------------------------------


def first_nonfinite_scalar(
    scalars: Mapping[str, Any]
) -> tuple[str, float] | None:
    """THE finite-check helper: first non-finite entry of a scalar map in
    root-cause order (``_SCALAR_ORDER`` first, then alphabetical), or
    None when everything is finite.  Both the loop's cadence check and
    its pre-save poisoned-state gate go through here."""
    order = [k for k in _SCALAR_ORDER if k in scalars] + sorted(
        k for k in scalars if k not in _SCALAR_ORDER
    )
    for name in order:
        try:
            value = float(np.asarray(scalars[name]))
        except (TypeError, ValueError):
            continue
        if not np.isfinite(value):
            return name, value
    return None


def tree_all_finite(tree: Any) -> bool:
    """Host-side: every leaf of a pytree finite (device_get as needed)."""
    for leaf in jax.tree.leaves(tree):
        if not bool(np.all(np.isfinite(np.asarray(jax.device_get(leaf))))):
            return False
    return True


# ---------------------------------------------------------------------------
# Provenance pass (failure path / debug CLI)
# ---------------------------------------------------------------------------

# Coarse topological rank for the RetinaNet family: the first non-finite
# layer is the EARLIEST one in forward order, and module paths don't carry
# execution order — this heuristic does (backbone stem → stages → fpn →
# heads → root outputs).
_TOP_RANK = {"backbone": 0, "fpn": 1, "cls_head": 2, "box_head": 2}
_STAGE_RE = re.compile(r"stage(\d+)")


def _path_str(path) -> str:
    return jax.tree_util.keystr(path)


def _layer_sort_key(path: str) -> tuple:
    names = re.findall(r"'([^']+)'", path)
    top = names[0] if names else ""
    rank = _TOP_RANK.get(top, 3)
    stage = 99
    if rank == 0:
        m = _STAGE_RE.search(path)
        if "stem" in path:
            stage = 0
        elif m:
            stage = int(m.group(1))
    return (rank, stage, path)


def _leaf_stats(value: Any) -> dict[str, Any]:
    arr = np.asarray(jax.device_get(value), dtype=np.float64)
    finite = np.isfinite(arr)
    n_bad = int(arr.size - int(finite.sum()))
    stats: dict[str, Any] = {"size": int(arr.size), "nonfinite": n_bad}
    if arr.size:
        stats["nan"] = int(np.isnan(arr).sum())
        stats["inf"] = n_bad - stats["nan"]
        if finite.any():
            fin = arr[finite]
            stats["min"] = float(fin.min())
            stats["max"] = float(fin.max())
            stats["absmax"] = float(np.abs(fin).max())
    return stats


def tree_report(tree: Any, max_entries: int = 256) -> dict[str, Any]:
    """Per-leaf non-finite/extremum stats for a pytree (params, grads).

    Returns ``{"nonfinite_total", "leaves", "first_nonfinite",
    "entries": {path: stats}}``; ``entries`` keeps every non-finite leaf
    plus the largest-magnitude finite ones up to ``max_entries`` (a full
    ResNet-50 table would be noise, the extremes are the signal)."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    rows: list[tuple[str, dict]] = []
    total_bad = 0
    for path, leaf in flat:
        stats = _leaf_stats(leaf)
        total_bad += stats["nonfinite"]
        rows.append((_path_str(path), stats))
    bad = [(p, s) for p, s in rows if s["nonfinite"]]
    bad.sort(key=lambda r: _layer_sort_key(r[0]))
    good = [(p, s) for p, s in rows if not s["nonfinite"]]
    good.sort(key=lambda r: -r[1].get("absmax", 0.0))
    entries = dict(bad[:max_entries])
    for p, s in good[: max(0, max_entries - len(entries))]:
        entries[p] = s
    return {
        "leaves": len(rows),
        "nonfinite_total": total_bad,
        "first_nonfinite": bad[0][0] if bad else None,
        "entries": entries,
    }


def forward_provenance(
    model, variables: Mapping[str, Any], images: Any, max_layers: int = 64
) -> dict[str, Any]:
    """One instrumented forward (flax ``capture_intermediates``) →
    per-layer activation stats, localizing the FIRST non-finite layer in
    (heuristic) forward order.  Replaces the ``--debug-nans`` rerun: the
    pass runs on the already-poisoned state/batch, eagerly, host-driven.
    """
    from batchai_retinanet_horovod_coco_tpu.data.pipeline import (
        normalize_images,
    )

    outputs, mutated = model.apply(
        dict(variables),
        normalize_images(jnp.asarray(images)),
        train=False,
        capture_intermediates=True,
        mutable=["intermediates"],
    )
    flat, _ = jax.tree_util.tree_flatten_with_path(
        mutated.get("intermediates", {})
    )
    layers: list[tuple[str, dict]] = []
    for path, value in flat:
        if not hasattr(value, "shape"):
            continue
        layers.append((_path_str(path), _leaf_stats(value)))
    bad = [(p, s) for p, s in layers if s["nonfinite"]]
    bad.sort(key=lambda r: _layer_sort_key(r[0]))
    out_stats = {
        k: _leaf_stats(v)
        for k, v in outputs.items()
        if hasattr(v, "shape")
    } if isinstance(outputs, Mapping) else {}
    return {
        "layers_inspected": len(layers),
        "nonfinite_layers": len(bad),
        "first_nonfinite_layer": bad[0][0] if bad else None,
        "layers": dict(bad[:max_layers]),
        "outputs": out_stats,
    }


def provenance(
    step: int,
    metrics: Mapping[str, Any] | None = None,
    params: Any | None = None,
    model=None,
    variables: Mapping[str, Any] | None = None,
    images: Any | None = None,
    image_ids: Any | None = None,
    rng_seed: int | None = None,
    tripped: Mapping[str, Any] | None = None,
    cadence: str | None = None,
) -> dict[str, Any]:
    """Assemble the NUMERICS_DUMP payload: scalar loss terms, the param
    tree report, and (when a model + batch are at hand) the instrumented
    forward — each section independent, so a partially available context
    still yields a useful dump."""
    dump: dict[str, Any] = {
        "event": "numerics_dump",
        "step": int(step),
        "tripped": dict(tripped) if tripped else None,
        "cadence": cadence,
        "rng_seed": rng_seed,
    }
    if image_ids is not None:
        dump["batch_image_ids"] = [int(i) for i in np.asarray(image_ids)]
        # The ids are the CHECK step's batch.  The finite-check runs at a
        # bounded cadence, so the poison may have entered up to a full
        # cadence window EARLIER — say so in the dump, or bad-input
        # triage inspects innocent images (review-round finding).
        dump["batch_image_ids_note"] = (
            "ids are from the step at which the finite-check TRIPPED; "
            "the non-finite value arose at or before this step"
            + (f" (checked {cadence})" if cadence else "")
        )
    scalars: dict[str, float] = {}
    if metrics:
        for k, v in metrics.items():
            try:
                scalars[k] = float(np.asarray(jax.device_get(v)))
            except (TypeError, ValueError):
                continue
        dump["metrics"] = scalars
        hit = first_nonfinite_scalar(scalars)
        dump["first_nonfinite_metric"] = hit[0] if hit else None
    if params is not None:
        dump["params"] = tree_report(params)
    if model is not None and variables is not None and images is not None:
        dump["forward"] = forward_provenance(model, variables, images)
    # The headline: the most specific localization available.
    fwd = dump.get("forward") or {}
    prm = dump.get("params") or {}
    dump["first_nonfinite"] = (
        fwd.get("first_nonfinite_layer")
        or prm.get("first_nonfinite")
        or dump.get("first_nonfinite_metric")
    )
    return dump


DUMP_NAME = "NUMERICS_DUMP.json"


def write_dump(dump: Mapping[str, Any], dump_dir: str) -> str:
    """Write ONE ``NUMERICS_DUMP.json`` into ``dump_dir`` (atomic: temp +
    rename, so a crash mid-abort never leaves a half-written dump)."""
    os.makedirs(dump_dir, exist_ok=True)
    path = os.path.join(dump_dir, DUMP_NAME)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(dump, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path


def load_dump(path: str) -> dict[str, Any]:
    with open(path) as f:
        return json.load(f)


def format_dump(dump: Mapping[str, Any]) -> str:
    """Human triage view of a dump — the whole of ``debug.py nans``."""
    lines = [
        f"numerics dump: step {dump.get('step')}"
        + (f" (checked {dump['cadence']})" if dump.get("cadence") else ""),
    ]
    tripped = dump.get("tripped")
    if tripped:
        lines.append(
            f"tripped: {tripped.get('metric')} = {tripped.get('value')}"
        )
    if dump.get("first_nonfinite"):
        lines.append(f"first non-finite: {dump['first_nonfinite']}")
    if dump.get("batch_image_ids") is not None:
        ids = dump["batch_image_ids"]
        shown = ", ".join(str(i) for i in ids[:16])
        more = f" (+{len(ids) - 16} more)" if len(ids) > 16 else ""
        lines.append(f"batch image ids: {shown}{more}")
        if dump.get("batch_image_ids_note"):
            lines.append(f"  note: {dump['batch_image_ids_note']}")
    if dump.get("rng_seed") is not None:
        lines.append(f"rng seed: {dump['rng_seed']}")
    metrics = dump.get("metrics") or {}
    if metrics:
        bad = {k: v for k, v in metrics.items() if not np.isfinite(v)}
        lines.append(
            "non-finite metrics: "
            + (", ".join(f"{k}={v}" for k, v in sorted(bad.items())) or "none")
        )
    for section, label in (("params", "param leaves"), ("forward", "layers")):
        sec = dump.get(section) or {}
        n = sec.get("nonfinite_total", sec.get("nonfinite_layers"))
        if n is None:
            continue
        lines.append(f"{section}: {n} non-finite {label}")
        table = sec.get("entries") or sec.get("layers") or {}
        for path, stats in list(table.items())[:8]:
            if stats.get("nonfinite"):
                lines.append(
                    f"  {path}: {stats['nonfinite']}/{stats['size']} "
                    f"non-finite (nan={stats.get('nan')}, "
                    f"inf={stats.get('inf')})"
                )
    return "\n".join(lines)
