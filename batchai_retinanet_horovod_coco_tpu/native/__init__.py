"""Native (C++) host kernels, compiled on demand and bound via ctypes.

SURVEY.md §2.5: the reference's native host code is pycocotools' C and the
Cython ``compute_overlap``; the rebuild's anchor-side IoU lives ON DEVICE
(ops/iou.py), and the eval-side hot loop lives here.
"""

from batchai_retinanet_horovod_coco_tpu.native.build import load_library

__all__ = ["load_library"]
