"""On-demand g++ compilation + ctypes loading of the native kernels.

No pybind11 in this environment (and no Python.h dependency wanted): the
kernels expose a plain C ABI and are bound with ctypes.  The .so is rebuilt
whenever the source is newer (mtime) and cached next to the source; if no
toolchain is available the caller falls back to its pure-numpy path, so the
framework never hard-requires a compiler at runtime.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_LOCK = threading.Lock()
_CACHE: dict[str, ctypes.CDLL | None] = {}


def _compile(src: str, lib: str) -> bool:
    tmp_path = None
    try:
        with tempfile.NamedTemporaryFile(
            suffix=".so", dir=_DIR, delete=False
        ) as tmp:
            tmp_path = tmp.name
        # No -march=native: a cached .so may travel to another host (rsync,
        # docker COPY preserve mtimes) where exotic ISA extensions would
        # SIGILL with no way to fall back.  -ffp-contract=off keeps bit
        # parity with the numpy oracle (no FMA contraction).
        cmd = [
            "g++", "-O3", "-shared", "-fPIC", "-ffp-contract=off",
            "-o", tmp_path, src,
        ]
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp_path, lib)  # atomic under concurrent builders
        return True
    except (OSError, subprocess.SubprocessError):
        if tmp_path is not None:
            try:
                os.unlink(tmp_path)
            except OSError:
                pass
        return False


def load_library(name: str = "cocoeval") -> ctypes.CDLL | None:
    """Load (building if stale) ``native/<name>.cpp`` → CDLL, or None."""
    with _LOCK:
        if name in _CACHE:
            return _CACHE[name]
        src = os.path.join(_DIR, f"{name}.cpp")
        lib = os.path.join(_DIR, f"lib{name}.so")
        result: ctypes.CDLL | None = None
        if os.path.exists(src):
            # Strict >: a fresh checkout gives .so and .cpp equal mtimes, and
            # a checked-out binary (wrong ISA, stale) must be rebuilt.
            fresh = os.path.exists(lib) and os.path.getmtime(
                lib
            ) > os.path.getmtime(src)
            if fresh or _compile(src, lib):
                try:
                    result = ctypes.CDLL(lib)
                except OSError:
                    result = None
        _CACHE[name] = result
        return result
