"""On-demand g++ compilation + ctypes loading of the native kernels.

No pybind11 in this environment (and no Python.h dependency wanted): the
kernels expose a plain C ABI and are bound with ctypes.  The .so is rebuilt
whenever the source is newer (mtime) and cached next to the source; if no
toolchain is available the caller falls back to its pure-numpy path, so the
framework never hard-requires a compiler at runtime.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_LOCK = threading.Lock()
_CACHE: dict[str, ctypes.CDLL | None] = {}


def _compile(src: str, lib: str, extra_flags: tuple[str, ...] = ()) -> bool:
    tmp_path = None
    try:
        with tempfile.NamedTemporaryFile(
            suffix=".so", dir=_DIR, delete=False
        ) as tmp:
            tmp_path = tmp.name
        # No -march=native: a cached .so may travel to another host (rsync,
        # docker COPY preserve mtimes) where exotic ISA extensions would
        # SIGILL with no way to fall back.  -ffp-contract=off keeps bit
        # parity with the numpy oracle (no FMA contraction).
        cmd = [
            "g++", "-O3", "-shared", "-fPIC", "-ffp-contract=off",
            *extra_flags, "-o", tmp_path, src,
        ]
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp_path, lib)  # atomic under concurrent builders
        return True
    except (OSError, subprocess.SubprocessError):
        if tmp_path is not None:
            try:
                os.unlink(tmp_path)
            except OSError:
                pass
        return False


_ASAN_FLAGS = ("-fsanitize=address", "-g", "-fno-omit-frame-pointer")


def load_library(name: str = "cocoeval", sanitize: bool = False) -> ctypes.CDLL | None:
    """Load (building if stale) ``native/<name>.cpp`` → CDLL, or None.

    ``sanitize=True`` builds an AddressSanitizer variant
    (``lib<name>_asan.so``) — the §5.2 sanitizer target for the native
    kernels (SURVEY.md).  Loading it requires libasan in the process
    (LD_PRELOAD for a stock Python); tests/unit/test_native_asan.py runs
    the kernels under it in a subprocess.
    """
    key = f"{name}+asan" if sanitize else name
    with _LOCK:
        if key in _CACHE:
            return _CACHE[key]
        src = os.path.join(_DIR, f"{name}.cpp")
        suffix = "_asan" if sanitize else ""
        lib = os.path.join(_DIR, f"lib{name}{suffix}.so")
        result: ctypes.CDLL | None = None
        if os.path.exists(src):
            # Strict >: a fresh checkout gives .so and .cpp equal mtimes, and
            # a checked-out binary (wrong ISA, stale) must be rebuilt.
            fresh = os.path.exists(lib) and os.path.getmtime(
                lib
            ) > os.path.getmtime(src)
            flags = _ASAN_FLAGS if sanitize else ()
            if fresh or _compile(src, lib, flags):
                try:
                    result = ctypes.CDLL(lib)
                except OSError:
                    result = None
        _CACHE[key] = result
        return result
