"""On-demand g++ compilation + ctypes loading of the native kernels.

No pybind11 in this environment (and no Python.h dependency wanted): the
kernels expose a plain C ABI and are bound with ctypes.  The .so is rebuilt
whenever the source is newer (mtime) and cached next to the source; if no
toolchain is available the caller falls back to its pure-numpy path, so the
framework never hard-requires a compiler at runtime.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_LOCK = threading.Lock()
_CACHE: dict[str, ctypes.CDLL | None] = {}


def _compile(src: str, lib: str) -> bool:
    with tempfile.NamedTemporaryFile(
        suffix=".so", dir=_DIR, delete=False
    ) as tmp:
        tmp_path = tmp.name
    cmd = [
        "g++", "-O3", "-march=native", "-shared", "-fPIC",
        # Bit parity with the numpy oracle: no FMA contraction.
        "-ffp-contract=off",
        "-o", tmp_path, src,
    ]
    try:
        subprocess.run(
            cmd, check=True, capture_output=True, timeout=120
        )
        os.replace(tmp_path, lib)  # atomic under concurrent builders
        return True
    except (OSError, subprocess.SubprocessError):
        try:
            os.unlink(tmp_path)
        except OSError:
            pass
        return False


def load_library(name: str = "cocoeval") -> ctypes.CDLL | None:
    """Load (building if stale) ``native/<name>.cpp`` → CDLL, or None."""
    with _LOCK:
        if name in _CACHE:
            return _CACHE[name]
        src = os.path.join(_DIR, f"{name}.cpp")
        lib = os.path.join(_DIR, f"lib{name}.so")
        result: ctypes.CDLL | None = None
        if os.path.exists(src):
            # Strict >: a fresh checkout gives .so and .cpp equal mtimes, and
            # a checked-out binary (wrong ISA, stale) must be rebuilt.
            fresh = os.path.exists(lib) and os.path.getmtime(
                lib
            ) > os.path.getmtime(src)
            if fresh or _compile(src, lib):
                try:
                    result = ctypes.CDLL(lib)
                except OSError:
                    result = None
        _CACHE[name] = result
        return result
