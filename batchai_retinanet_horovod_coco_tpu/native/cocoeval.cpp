// Native COCOeval bbox kernels: pairwise IoU + greedy threshold matching.
//
// TPU-native-framework host extension replacing the reference stack's native
// eval pieces (SURVEY.md §2.5: pycocotools _mask.c / maskApi.c bbox-IoU path
// and the Cython compute_overlap) for the HOST side of evaluation.  The
// device side (decode/NMS) is XLA; this covers the per-(image,category)
// matching loop that dominates COCOeval wall-time on a 5k-image val set.
//
// Semantics mirror batchai_retinanet_horovod_coco_tpu/evaluate/coco_eval.py
// (the numpy oracle) exactly; tests/unit/test_native_cocoeval.py asserts
// bit-identical outputs on randomized fixtures.  Compiled on demand by
// evaluate/_native.py (g++ -O3 -shared); no Python.h dependency — plain C ABI
// via ctypes.

#include <cstdint>

extern "C" {

// Pairwise IoU of xywh boxes, crowd-aware (crowd gt: denominator = det area).
// dt: D*4, gt: G*4, iscrowd: G, out: D*G (det-major).
void iou_matrix_xywh(const double* dt, int64_t D, const double* gt, int64_t G,
                     const uint8_t* iscrowd, double* out) {
  for (int64_t d = 0; d < D; ++d) {
    const double dx1 = dt[d * 4 + 0], dy1 = dt[d * 4 + 1];
    const double dw = dt[d * 4 + 2], dh = dt[d * 4 + 3];
    const double dx2 = dx1 + dw, dy2 = dy1 + dh;
    const double d_area = dw * dh;
    for (int64_t g = 0; g < G; ++g) {
      const double gx1 = gt[g * 4 + 0], gy1 = gt[g * 4 + 1];
      const double gw = gt[g * 4 + 2], gh = gt[g * 4 + 3];
      const double gx2 = gx1 + gw, gy2 = gy1 + gh;
      const double iw_hi = (dx2 < gx2 ? dx2 : gx2) - (dx1 > gx1 ? dx1 : gx1);
      const double ih_hi = (dy2 < gy2 ? dy2 : gy2) - (dy1 > gy1 ? dy1 : gy1);
      const double iw = iw_hi > 0.0 ? iw_hi : 0.0;
      const double ih = ih_hi > 0.0 ? ih_hi : 0.0;
      const double inter = iw * ih;
      const double uni =
          iscrowd[g] ? d_area : d_area + gw * gh - inter;
      out[d * G + g] = uni > 0.0
                           ? inter / (uni > 1e-12 ? uni : 1e-12)
                           : 0.0;
    }
  }
}

// Greedy COCOeval matching for all T thresholds at once.
//
// Inputs are in the SAME layout the numpy oracle uses after its sorts:
// dets score-sorted (descending), gts ignore-sorted (non-ignored first).
// ious: D*G det-major. iou_thrs: T. g_ignore/g_crowd: G.
// Outputs (caller-allocated): dtm/gtm int64 T*D / T*G filled with the
// matched counterpart index or -1; dt_ignore uint8 T*D.
void match_detections(const double* ious, int64_t D, int64_t G,
                      const double* iou_thrs, int64_t T,
                      const uint8_t* g_ignore, const uint8_t* g_crowd,
                      int64_t* dtm, int64_t* gtm, uint8_t* dt_ignore) {
  for (int64_t i = 0; i < T * D; ++i) dtm[i] = -1;
  for (int64_t i = 0; i < T * G; ++i) gtm[i] = -1;
  for (int64_t i = 0; i < T * D; ++i) dt_ignore[i] = 0;

  for (int64_t t = 0; t < T; ++t) {
    const double thr = iou_thrs[t];
    for (int64_t d = 0; d < D; ++d) {
      // Match at IoU >= thr; 1-1e-10 cap mirrors pycocotools.
      double best = thr < 1.0 - 1e-10 ? thr : 1.0 - 1e-10;
      int64_t m = -1;
      const double* row = ious + d * G;
      for (int64_t g = 0; g < G; ++g) {
        if (gtm[t * G + g] >= 0 && !g_crowd[g]) continue;
        if (m > -1 && !g_ignore[m] && g_ignore[g]) break;
        if (row[g] < best) continue;
        best = row[g];
        m = g;
      }
      if (m == -1) continue;
      dtm[t * D + d] = m;
      gtm[t * G + m] = d;
      dt_ignore[t * D + d] = g_ignore[m];
    }
  }
}

}  // extern "C"
