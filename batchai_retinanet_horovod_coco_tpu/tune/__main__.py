"""Schedule-search CLI: ``python -m batchai_retinanet_horovod_coco_tpu.tune``.

Three jobs, one command (RUNBOOK "Autotuning schedules"):

- **search** (default): measure candidates for the requested ops on THIS
  process's device, compose the winners + full trial log into a
  schema-valid artifact, and save it to the per-device registry
  (``artifacts/schedules/<device_kind>.json``) — consumers pick it up on
  their next process start.  ``--dry-run`` prints without writing.
- **--bench-out TUNEBENCH.json**: additionally commit a regression
  tripwire record (the NMS winner's measured ms/batch), the tune/ twin of
  BUCKETBENCH/EVALBENCH/SERVEBENCH.
- **--check**: re-measure the committed TUNEBENCH winner and enforce the
  noise band (``make tunebench-check``) — same device-class guard as
  bench-check: a record captured on a different device class passes with
  a loud re-capture note instead of failing the run.

Outage contract is bench.py's, reused directly: subprocess probe before
any in-process device work, UNAVAILABLE-class errors in any phase emit
ONE structured JSON line with the committed last-known-good attached and
exit 75 (EX_TEMPFAIL) — never an rc-1 traceback.  ``--smoke`` skips the
probe (CPU path, ``make tune-smoke``).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# 3% noise band, bench.py's tripwire policy (TUNEBENCH measures ms/batch,
# lower-better, so the band is applied as a ceiling: committed * 1.03).
NOISE_BAND_PCT = 3.0
EXIT_TPU_UNREACHABLE = 75


def _repo_root() -> str:
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def _bench_module():
    """bench.py's probe/outage machinery, imported from the repo root
    (it is a top-level driver, not a package module)."""
    try:
        import bench  # noqa: F401 — already importable (tests, repo cwd)
        return bench
    except ImportError:
        root = _repo_root()
        if root not in sys.path and os.path.exists(
            os.path.join(root, "bench.py")
        ):
            sys.path.insert(0, root)
            try:
                import bench
                return bench
            except ImportError:
                pass
    return None


def _tunebench_path(explicit: str | None) -> str:
    return explicit or os.path.join(_repo_root(), "TUNEBENCH.json")


def _last_known_good(path: str) -> dict | None:
    try:
        with open(path) as f:
            data = json.load(f)
        return {
            "value": float(data["value"]),
            "source": os.path.basename(path),
            "note": "committed last-known-good, NOT a fresh measurement",
        }
    except (OSError, KeyError, ValueError):
        return None


def _emit_unreachable(phase: str, error: str, bench_out: str) -> int:
    """The one structured outage line (bench.py's schema, mode "tune")."""
    print(
        json.dumps(
            {
                "error": "tpu_unreachable",
                "mode": "tune",
                "phase": phase,
                "metric": "nms_postprocess_ms_per_batch",
                "attempts": 1,
                "last_error": str(error)[-2000:],
                "last_known_good": _last_known_good(bench_out),
                "exit_code": EXIT_TPU_UNREACHABLE,
            }
        ),
        flush=True,
    )
    return EXIT_TPU_UNREACHABLE


def _ops_from_report(path: str) -> tuple[list[str], bool]:
    """PERF_REPORT.json (obs/analyze) → (op families, search batch axis).

    The perf doctor's top-3 bottleneck verdict names the ``tune/``
    problems to attack (``tune_ops`` per entry: nms/focal/matching/
    batch); this is the loop-closing consumer — ``--from-report`` turns a
    run's own attribution into the next search instead of a hand-picked
    --ops list.  Ops come back deduplicated in rank order; ``batch``
    maps onto the --batch-axis search rather than an op family.
    Raises SystemExit on an unreadable report or an empty verdict (an
    explicit "nothing tunable" beats silently searching everything).
    """
    ops: list[str] = []
    names: list[str] = []
    batch_axis = False
    try:
        with open(path) as f:
            report = json.load(f)
        # TypeError/AttributeError cover structurally-wrong JSON (a
        # top-level array, string entries): every malformation gets the
        # same friendly SystemExit, never a raw traceback.
        for b in report["bottlenecks"]:
            names.append(str(b.get("name")))
            for op in b.get("tune_ops") or []:
                if op == "batch":
                    batch_axis = True
                elif op not in ops:
                    ops.append(op)
    except (OSError, ValueError, KeyError, TypeError, AttributeError) as e:
        raise SystemExit(f"--from-report: cannot read {path!r}: {e}")
    if not ops and not batch_axis:
        raise SystemExit(
            f"--from-report: {path!r} names no tunable ops in its top-3 "
            f"verdict ({names}) — nothing for the tuner to attack; run "
            "the search explicitly with --ops"
        )
    return ops, batch_axis


def _parse_hw(text: str) -> tuple[int, int]:
    try:
        h, w = text.lower().split("x")
        return int(h), int(w)
    except ValueError:
        raise SystemExit(f"--hw: not an HxW shape: {text!r}") from None


def _check(args, search_lib) -> int:
    """tunebench-check: re-measure the committed winner, enforce the band."""
    path = _tunebench_path(args.bench_out)
    try:
        with open(path) as f:
            committed = json.load(f)
        committed_ms = float(committed["value"])
    except (OSError, KeyError, ValueError) as e:
        print(f"# tunebench-check: cannot read committed record: {e}")
        return 1
    import jax

    device_kind = jax.devices()[0].device_kind
    committed_device = committed.get("device_kind")
    # bench.py's _check_floor device-class guard, ms-ceiling edition:
    # cross-device latencies are not comparable, so mismatches pass loudly.
    if committed_device != device_kind:
        print(
            f"# tunebench-check: committed record was captured on "
            f"{committed_device or 'an unrecorded accelerator'!r} but this "
            f"run is on {device_kind!r}; latencies are not comparable "
            "across device classes — re-capture with `make tunebench`"
        )
        return 0
    hw = tuple(committed.get("hw", list(search_lib.DEFAULT_HW)))
    batch = int(committed.get("batch", search_lib.DEFAULT_BATCH))
    winner = dict(committed.get("winner", {"impl": "xla"}))
    trial = search_lib.run_trial(
        "nms", winner, search_lib._nms_builder(batch, hw), args.steps
    )
    if trial.status != "ok":
        print(f"# tunebench-check: re-measurement failed: {trial.error}")
        return 1
    # Noise-aware ceiling: the committed record's own two-window spread is
    # its measured noise floor (bench.py's window policy), so the band is
    # max(3%, that spread) — on the chip (~0.3% spread) this keeps bench-
    # check's 3% teeth; on a noisy CPU fallback it stops scheduler jitter
    # from reading as regression.  The fresh side compares its BEST window:
    # a real regression slows every window, a descheduled one doesn't.
    band_pct = max(NOISE_BAND_PCT, float(committed.get("noise_pct") or 0.0))
    fresh = min(trial.window_ms) if trial.window_ms else trial.ms_per_call
    ceiling = committed_ms * (1 + band_pct / 100)
    verdict = "ok" if fresh <= ceiling else "REGRESSION"
    print(
        f"# tunebench-check: {fresh:.2f} ms/batch (best window of "
        f"{trial.window_ms}) vs committed {committed_ms:.2f} (ceiling "
        f"{ceiling:.2f} = +{band_pct:.2f}%): {verdict}"
    )
    return 0 if verdict == "ok" else 1


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m batchai_retinanet_horovod_coco_tpu.tune",
        description="measured schedule search → per-device registry artifact",
    )
    ap.add_argument(
        "--ops", default=None,
        help="comma list of op families to search (default "
             "nms,focal,matching, or the --from-report verdict)",
    )
    ap.add_argument(
        "--batch-axis", action="store_true",
        help="also search per-bucket batch sizes (eval/serve tables)",
    )
    ap.add_argument(
        "--from-report", default=None, metavar="PERF_REPORT.json",
        help="derive the search from a perf-doctor report's top-3 "
             "bottleneck verdict (obs/analyze): the union of its "
             "tune_ops in rank order; a 'batch' op enables --batch-axis. "
             "An explicit --ops overrides",
    )
    ap.add_argument("--hw", default=None, metavar="HxW",
                    help="bucket to measure at (default: flagship 800x1344)")
    ap.add_argument("--batch", type=int, default=None,
                    help="batch size for op trials (default 8)")
    ap.add_argument("--steps", type=int, default=None,
                    help="timed calls per trial, split into two windows")
    ap.add_argument(
        "--include-semantic", action="store_true",
        help="also measure non-default pre_nms_size values (recorded as "
             "semantics-approx trials; never auto-promoted to winner)",
    )
    ap.add_argument(
        "--smoke", action="store_true",
        help="CPU-sized smoke: tiny bucket/steps, no probe — proves the "
             "search end-to-end and commits an xla-winner artifact",
    )
    ap.add_argument("--device-kind", default=None,
                    help="override the artifact's device_kind (tests)")
    ap.add_argument("--out-root", default=None,
                    help="registry dir (default artifacts/schedules/)")
    ap.add_argument("--dry-run", action="store_true",
                    help="print the artifact instead of writing it")
    ap.add_argument("--bench-out", default=None, metavar="TUNEBENCH.json",
                    help="also write the tripwire record here")
    ap.add_argument("--check", action="store_true",
                    help="tunebench-check mode: re-measure the committed "
                         "TUNEBENCH winner and enforce the noise band")
    ap.add_argument("--trace", "--obs-trace", action="store_true",
                    dest="trace",
                    help="record tune_search/tune_trial spans to a "
                         "Perfetto-loadable trace in --obs-dir")
    ap.add_argument("--obs-dir", default="artifacts/obs",
                    help="where --trace writes its artifacts")
    args = ap.parse_args(argv)

    if args.from_report is not None and args.ops is None:
        report_ops, report_batch_axis = _ops_from_report(args.from_report)
        args.ops = ",".join(report_ops)
        args.batch_axis = args.batch_axis or report_batch_axis
        print(
            f"# tune: --from-report {args.from_report} -> "
            f"ops={args.ops or '(none)'} batch_axis={args.batch_axis}",
            flush=True,
        )
    if args.ops is None:
        args.ops = "nms,focal,matching"

    # Smoke defaults: small enough that a 2-vCPU box finishes in seconds.
    hw = _parse_hw(args.hw) if args.hw else ((256, 256) if args.smoke else None)
    batch = args.batch if args.batch is not None else (2 if args.smoke else None)
    steps = args.steps if args.steps is not None else (4 if args.smoke else None)
    args.steps = steps if steps is not None else 30

    from batchai_retinanet_horovod_coco_tpu.obs import trace as obs_trace

    if args.trace:
        obs_trace.configure(args.obs_dir, process_label="tune")

    bench = _bench_module()
    bench_out = _tunebench_path(args.bench_out)
    # bench.py's subprocess probe: a dead tunnel can HANG in-process
    # backend init, which only a subprocess can bound.  It guards --check
    # too (the check's own jax.devices() would be the unbounded hang);
    # only --smoke skips it (CPU path, no tunnel to die).
    if (
        not args.smoke
        and bench is not None
        and os.environ.get("BENCH_PROBE", "1") not in ("", "0")
    ):
        attempts, err = bench.probe_device()
        if err is not None:
            return _emit_unreachable("probe", err, bench_out)

    try:
        from batchai_retinanet_horovod_coco_tpu.tune import search as search_lib

        if args.check:
            return _check(args, search_lib)

        kwargs = {}
        if hw is not None:
            kwargs["hw"] = hw
        if batch is not None:
            kwargs["batch"] = batch
        doc = search_lib.run_search(
            ops=tuple(p for p in args.ops.split(",") if p),
            steps=args.steps,
            include_semantic=args.include_semantic,
            search_batches=args.batch_axis,
            device_kind=args.device_kind,
            **kwargs,
        )

        from batchai_retinanet_horovod_coco_tpu.tune import (
            schedule as schedule_lib,
        )

        summary = {
            "device_kind": doc["device_kind"],
            "entries": doc["entries"],
            "trials": len(doc["trials"]),
            "failed": sum(
                1 for t in doc["trials"] if t["status"] == "failed"
            ),
            "skipped": sum(
                1 for t in doc["trials"] if t["status"] == "skipped"
            ),
        }
        if args.dry_run:
            print(json.dumps(doc, indent=2, sort_keys=True))
            return 0
        path = schedule_lib.save_schedule(doc, args.out_root)
        summary["artifact"] = path
        print(json.dumps(summary, sort_keys=True), flush=True)

        if args.bench_out is not None:
            nms_trials = [
                t for t in doc["trials"]
                if t["op"] == "nms" and t["status"] == "ok"
                and t["params"] == doc["entries"].get("nms")
            ]
            if not nms_trials:
                print("# tunebench: no NMS winner trial to commit")
                return 1
            win = nms_trials[0]
            record = {
                "metric": "nms_postprocess_ms_per_batch",
                "mode": "tune",
                "value": win["ms_per_call"],
                "unit": "ms/batch (lower is better)",
                "device_kind": doc["device_kind"],
                "hw": list(hw or search_lib.DEFAULT_HW),
                "batch": batch or search_lib.DEFAULT_BATCH,
                "steps": args.steps,
                "noise_pct": win["noise_pct"],
                "winner": doc["entries"]["nms"],
                "schedule_artifact": os.path.relpath(path, _repo_root()),
            }
            from batchai_retinanet_horovod_coco_tpu.utils.atomicio import (
                atomic_write_text,
            )

            atomic_write_text(
                bench_out,
                json.dumps(record, indent=2, sort_keys=True) + "\n",
            )
            print(f"# tunebench record written to {bench_out}")
        return 0
    except SystemExit:
        raise
    except Exception as e:
        # The probe can pass and the device die mid-search — still an
        # outage, not a tuner bug (bench.py's mid-run contract).
        from batchai_retinanet_horovod_coco_tpu.tune import search as search_lib

        if isinstance(e, search_lib.DeviceUnavailable) or (
            bench is not None and bench.is_unavailable_error(e)
        ):
            return _emit_unreachable("mid-run", str(e), bench_out)
        raise
    finally:
        if args.trace:
            obs_trace.export()
            merged = obs_trace.merge_traces(out_name="tune_trace.json")
            print(f"# trace written to {merged}", flush=True)


if __name__ == "__main__":
    sys.exit(main())
