"""Measured schedule search + per-device schedule registry (ISSUE 6).

The TVM lesson (PAPERS.md): kernel/batch schedules are SEARCHED per
device, not hand-picked, and winners are versioned artifacts.  This
package owns that loop for the repo's tunable hot-path parameters —
Pallas tile/block shapes (focal, matching, NMS), ``pre_nms_size``, and
per-bucket batch sizes:

- ``schedule``   — the persistent registry: one schema-validated JSON per
  ``device_kind`` under ``artifacts/schedules/``, deep-merged over the
  built-in defaults at lookup; unknown devices fall back to defaults with
  ONE loud structured event, never a crash.  Import-light (no jax).
- ``candidates`` — candidate generation per op family.
- ``search``     — the timed search harness: AOT-compile each candidate,
  two disjoint timed windows (bench.py's noise policy), trial spans/events
  through obs, bench.py's probe/outage contract (exit 75 on a dead
  tunnel), winner composition into a registry artifact.

Consumers look winners up instead of hardcoding: ``train/step.py``
(matching/focal kernel params), ``evaluate/detect.py`` + ``serve/engine.py``
(NMS impl/block, ``pre_nms_size``, per-bucket batch sizes) and
``convert_model.py`` (schedule provenance recorded in the export
manifest).  CLI: ``python -m batchai_retinanet_horovod_coco_tpu.tune``
(``make tune-smoke`` / ``make tunebench`` / ``make tunebench-check``;
RUNBOOK "Autotuning schedules").
"""

from batchai_retinanet_horovod_coco_tpu.tune.schedule import (
    DEFAULT_SCHEDULE,
    ScheduleError,
    eval_batch_for,
    load_schedule,
    lookup,
    provenance,
    save_schedule,
    schedule_path,
    serve_batch_sizes_for,
    validate_schedule,
)

__all__ = [
    "DEFAULT_SCHEDULE",
    "ScheduleError",
    "eval_batch_for",
    "load_schedule",
    "lookup",
    "provenance",
    "save_schedule",
    "schedule_path",
    "serve_batch_sizes_for",
    "validate_schedule",
]
