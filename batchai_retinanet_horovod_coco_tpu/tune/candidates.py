"""Candidate generation for the schedule search (tune/search.py).

Each op family yields a small explicit list of candidate parameter dicts
— the spaces are tiny (tile shapes bounded by VMEM, impls by what exists)
so the search is exhaustive-by-default rather than sampled; TVM-style
learned cost models are unwarranted at this scale.  Every candidate dict
is directly mergeable into the schedule registry's ``entries[op]``
(tune/schedule.py), so the winner IS the artifact entry.

Semantics notes per axis:

- ``impl`` and tile/block sizes are performance-only: every impl pair is
  bit-identical (the parity suites pin it), so the search may pick freely.
- ``pre_nms_size`` CHANGES DETECTION SEMANTICS (fewer candidates survive
  to NMS → mAP can move).  It is still a legitimate axis — the reference
  hand-picked 1000 with no measurement — but non-default values are only
  emitted when the caller opts in (``include_semantic=True``), and the
  search records them as ``semantics: "approx"`` trials so a human
  committing a winner sees the tradeoff (RUNBOOK "Autotuning schedules").
"""

from __future__ import annotations

from typing import Any, Iterable

# VMEM-bounded tile menus.  Focal backward holds more live temps than
# forward (grad + recomputed p/log terms), hence the smaller ceiling —
# see ops/pallas/focal.py's FWD/BWD_TILE_A notes.
NMS_BLOCKS = (128, 256, 512)
FOCAL_FWD_TILES = (4096, 8192, 16384)
FOCAL_BWD_TILES = (2048, 4096)
MATCHING_TILES = (4096, 8192, 16384)
PRE_NMS_SIZES = (512, 1000, 2048)
BATCH_SIZES = (2, 4, 8, 16)


def nms_candidates(
    include_semantic: bool = False,
    blocks: Iterable[int] = NMS_BLOCKS,
    pre_nms_sizes: Iterable[int] = PRE_NMS_SIZES,
) -> list[dict[str, Any]]:
    """XLA baseline + one kernel candidate per block size (× pre_nms when
    the caller opts into the semantics-affecting axis)."""
    pres = tuple(pre_nms_sizes) if include_semantic else (1000,)
    out: list[dict[str, Any]] = []
    for pre in pres:
        out.append({"impl": "xla", "pre_nms_size": pre})
        for blk in blocks:
            out.append({"impl": "pallas", "block_k": blk, "pre_nms_size": pre})
    return out


def focal_candidates(
    fwd_tiles: Iterable[int] = FOCAL_FWD_TILES,
    bwd_tiles: Iterable[int] = FOCAL_BWD_TILES,
) -> list[dict[str, Any]]:
    out: list[dict[str, Any]] = [{"impl": "xla"}]
    for fwd in fwd_tiles:
        for bwd in bwd_tiles:
            out.append({"impl": "pallas", "fwd_tile_a": fwd, "bwd_tile_a": bwd})
    return out


def matching_candidates(
    tiles: Iterable[int] = MATCHING_TILES,
) -> list[dict[str, Any]]:
    out: list[dict[str, Any]] = [{"impl": "xla"}]
    for tile in tiles:
        out.append({"impl": "pallas", "tile_a": tile})
    return out


def batch_candidates(sizes: Iterable[int] = BATCH_SIZES) -> list[dict[str, Any]]:
    """Per-bucket batch-size axis (eval/detect throughput per chip)."""
    return [{"batch": b} for b in sizes]


def candidates_for(op: str, **kwargs: Any) -> list[dict[str, Any]]:
    try:
        fn = {
            "nms": nms_candidates,
            "focal": focal_candidates,
            "matching": matching_candidates,
            "batch": batch_candidates,
        }[op]
    except KeyError:
        raise ValueError(
            f"unknown op {op!r}; known: batch, focal, matching, nms"
        ) from None
    return fn(**kwargs)
