"""Timed schedule search (ISSUE 6): measure candidates, compose a winner.

The TVM lesson (PAPERS.md) applied at this repo's scale: the tunable
hot-path parameters — Pallas tile/block shapes for focal, matching and
NMS, ``pre_nms_size``, per-bucket batch sizes — are cheap enough to
search EXHAUSTIVELY (tune/candidates.py's menus are a handful of entries
each), so the harness is a measured argmin, not a learned cost model.

Measurement policy is bench.py's, not a new one:

- **AOT compile first** (``jax.jit(...).lower(...).compile()``), so a
  trial never times tracing;
- **two disjoint timed windows** with a hard device sync inside each
  timed region; the point estimate is the combined rate and the
  window-to-window spread is reported per trial as its noise floor;
- timestamps come from THE project clock (``obs.trace.monotonic_s``) and
  every trial runs under a ``tune_trial`` span, so a search shows up in
  Perfetto as one track of compile+window spans per candidate (RUNBOOK
  "Autotuning schedules").

Error policy: a candidate that fails to compile or run is a FAILED TRIAL
(recorded, skipped) — a too-big tile must not kill the search — EXCEPT
accelerator-unreachable errors (bench.py's UNAVAILABLE classification),
which raise :class:`DeviceUnavailable` so the CLI can exit 75 with the
structured outage line instead of composing a winner from a dead device.

Semantics policy (tune/candidates.py): ``pre_nms_size`` changes detection
semantics, so non-default values are measured only when the caller opts
in, every such trial is recorded with ``semantics: "approx"``, and the
WINNER is always chosen among exact-semantics trials — a human promotes
an approx trial to a winner deliberately, never the harness.

Pallas candidates only run where Mosaic exists (TPU): elsewhere they are
recorded as skipped trials and the winner comes from the XLA candidates —
which is exactly what a CPU smoke run (``make tune-smoke``) commits.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from batchai_retinanet_horovod_coco_tpu.obs import trace
from batchai_retinanet_horovod_coco_tpu.tune import candidates as cand_lib
from batchai_retinanet_horovod_coco_tpu.tune import schedule as schedule_lib

# Matches bench.py's flagship bucket; the search defaults to measuring
# where the train/serve money is.
DEFAULT_HW = (800, 1344)
DEFAULT_BATCH = 8
DEFAULT_STEPS = 30  # per trial, split into two windows

# bench.py's outage vocabulary, duplicated as data (not imported: bench.py
# is a repo-root script, and this module must import cleanly from an
# installed package).  tests/unit/test_tune.py pins the two sets equal.
UNAVAILABLE_MARKERS = (
    "unavailable",
    "unable to initialize backend",
    "deadline_exceeded",
    "failed to connect",
    "backend init hang",
)


class DeviceUnavailable(RuntimeError):
    """A trial died because the accelerator became unreachable — the
    search must stop and the CLI must exit 75, not record a winner."""


def _is_unavailable(err: BaseException) -> bool:
    # Whole __cause__/__context__ chain, exactly like bench.py's
    # classifier: jax re-wraps the backend-init UNAVAILABLE RuntimeError
    # one link down (the BENCH_r05 crash class), and a chain-wrapped
    # outage misread as a failed trial would cascade into an rc-1
    # "no successful trial" crash instead of the exit-75 contract.
    seen: set[int] = set()
    stack: list = [err]
    while stack:
        e = stack.pop()
        if e is None or id(e) in seen:
            continue
        seen.add(id(e))
        text = str(e).lower()
        if any(m in text for m in UNAVAILABLE_MARKERS):
            return True
        stack.extend((e.__cause__, e.__context__))
    return False


@dataclasses.dataclass
class Trial:
    """One measured candidate (the artifact's ``trials`` records these)."""

    op: str
    params: dict[str, Any]
    ms_per_call: float | None
    window_ms: list[float]
    noise_pct: float | None
    semantics: str = "exact"
    status: str = "ok"  # "ok" | "failed" | "skipped"
    error: str | None = None

    def record(self) -> dict[str, Any]:
        return {
            "op": self.op,
            "params": self.params,
            "ms_per_call": self.ms_per_call,
            "window_ms": self.window_ms,
            "noise_pct": self.noise_pct,
            "semantics": self.semantics,
            "status": self.status,
            "error": self.error,
        }


def mosaic_available() -> bool:
    """Pallas TPU kernels need Mosaic — i.e. an actual TPU backend."""
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def time_compiled(fn: Callable[[], Any], steps: int) -> tuple[float, list[float]]:
    """Two disjoint timed windows over an already-compiled nullary call;
    returns (ms_per_call, [window_ms, window_ms]).  Syncs inside each
    window (bench.py's policy: dispatch half the steps, one hard sync)."""
    half = max(1, steps // 2)
    window_ms: list[float] = []
    for _ in range(2):
        with trace.span("tune_window", steps=half):
            t0 = trace.monotonic_s()
            out = None
            for _ in range(half):
                out = fn()
            jax.block_until_ready(out)
            dt = trace.monotonic_s() - t0
        window_ms.append(dt / half * 1e3)
    return sum(window_ms) / len(window_ms), window_ms


def run_trial(
    op: str,
    params: dict[str, Any],
    build: Callable[[dict[str, Any]], Callable[[], Any]],
    steps: int,
    semantics: str = "exact",
) -> Trial:
    """Compile + warm + time one candidate; failures become failed trials
    unless the device itself went away (:class:`DeviceUnavailable`)."""
    with trace.span("tune_trial", op=op, **{
        k: v for k, v in params.items() if isinstance(v, (int, str))
    }):
        try:
            with trace.span("tune_compile", op=op):
                fn = build(params)
                out = fn()  # warmup call 1 (first real dispatch)
                out = fn()  # warmup call 2 (autotune/cache settled)
                jax.block_until_ready(out)
            ms, window_ms = time_compiled(fn, steps)
        except Exception as e:  # noqa: BLE001 — classified below
            if _is_unavailable(e):
                raise DeviceUnavailable(str(e)) from e
            return Trial(
                op=op, params=params, ms_per_call=None, window_ms=[],
                noise_pct=None, semantics=semantics, status="failed",
                error=str(e)[-500:],
            )
    noise = (
        abs(window_ms[0] - window_ms[1]) / max(ms, 1e-9) * 100
        if len(window_ms) == 2
        else None
    )
    return Trial(
        op=op, params=params, ms_per_call=round(ms, 3),
        window_ms=[round(w, 3) for w in window_ms],
        noise_pct=round(noise, 2) if noise is not None else None,
        semantics=semantics,
    )


# ---------------------------------------------------------------------------
# Per-op trial programs (synthetic inputs, bench.py's distributions)
# ---------------------------------------------------------------------------


def _postprocess_inputs(batch: int, hw: tuple[int, int]):
    """The NMS search's input field: bench.run_postprocess_bucket's
    realistic sparse score distribution (sigmoid(-4 ± 1) ≈ 2% foreground)
    over the flagship anchor grid."""
    from batchai_retinanet_horovod_coco_tpu.evaluate.detect import DetectConfig
    from batchai_retinanet_horovod_coco_tpu.ops import anchors as anchors_lib

    cfg = DetectConfig()
    anchors = anchors_lib.anchors_for_image_shape(hw, cfg.anchor)
    rng = np.random.default_rng(1)
    cls = jnp.asarray(
        rng.normal(-4.0, 1.0, (batch, anchors.shape[0], 80)).astype(np.float32)
    )
    deltas = jnp.asarray(
        rng.normal(0.0, 0.3, (batch, anchors.shape[0], 4)).astype(np.float32)
    )
    return jnp.asarray(anchors), cls, deltas


def _nms_builder(
    batch: int, hw: tuple[int, int]
) -> Callable[[dict[str, Any]], Callable[[], Any]]:
    from batchai_retinanet_horovod_coco_tpu.evaluate.detect import (
        DetectConfig,
        nms_fn_for,
    )
    from batchai_retinanet_horovod_coco_tpu.ops import boxes as boxes_lib

    anchors_dev, cls, deltas = _postprocess_inputs(batch, hw)

    def build(params: dict[str, Any]) -> Callable[[], Any]:
        # Every schedule knob pinned explicitly: the trial must measure
        # THIS candidate, not whatever the registry currently holds.
        cfg = DetectConfig(
            pre_nms_size=int(params.get("pre_nms_size", 1000)),
            nms_impl=str(params["impl"]),
            nms_block_k=int(params.get("block_k", 256)),
        )
        nms = nms_fn_for(cfg)

        def post(cls_logits, box_deltas):
            scores = jax.nn.sigmoid(cls_logits)
            boxes = boxes_lib.decode_boxes(
                anchors_dev[None], box_deltas, cfg.codec
            )
            boxes = boxes_lib.clip_boxes(boxes, hw)
            return nms(boxes, scores)

        compiled = jax.jit(post).lower(cls, deltas).compile()
        return lambda: compiled(cls, deltas)

    return build


def _focal_builder(
    batch: int, hw: tuple[int, int]
) -> Callable[[dict[str, Any]], Callable[[], Any]]:
    from batchai_retinanet_horovod_coco_tpu import losses as losses_lib
    from batchai_retinanet_horovod_coco_tpu.ops import anchors as anchors_lib

    num_anchors = anchors_lib.anchors_for_image_shape(hw).shape[0]
    rng = np.random.default_rng(2)
    logits = jnp.asarray(
        rng.normal(-4.0, 1.0, (batch, num_anchors, 80)).astype(np.float32)
    )
    labels = jnp.asarray(
        rng.integers(0, 80, (batch, num_anchors)).astype(np.int32)
    )
    # ~1% positive, ~4% ignored — a realistic assignment mix.
    state = jnp.asarray(
        rng.choice(
            np.array([-1, 0, 1], np.int32),
            (batch, num_anchors),
            p=[0.04, 0.95, 0.01],
        )
    )

    def build(params: dict[str, Any]) -> Callable[[], Any]:
        config = losses_lib.LossConfig(
            pallas_focal=params["impl"] == "pallas",
            focal_fwd_tile_a=params.get("fwd_tile_a"),
            focal_bwd_tile_a=params.get("bwd_tile_a"),
        )

        def loss_and_grad(x):
            # fwd + bwd: the train step always pays both.
            return jax.value_and_grad(
                lambda lg: jnp.sum(
                    losses_lib.focal_loss_compact(lg, labels, state, config)
                )
            )(x)

        compiled = jax.jit(loss_and_grad).lower(logits).compile()
        return lambda: compiled(logits)

    return build


def _matching_builder(
    batch: int, hw: tuple[int, int], num_gt: int = 32
) -> Callable[[dict[str, Any]], Callable[[], Any]]:
    from batchai_retinanet_horovod_coco_tpu.ops import anchors as anchors_lib
    from batchai_retinanet_horovod_coco_tpu.ops import matching as matching_lib

    anchors = jnp.asarray(anchors_lib.anchors_for_image_shape(hw))
    rng = np.random.default_rng(3)
    x1 = rng.uniform(0, hw[1] * 0.8, (batch, num_gt, 1))
    y1 = rng.uniform(0, hw[0] * 0.8, (batch, num_gt, 1))
    wh = rng.uniform(16, 256, (batch, num_gt, 2))
    gt_boxes = jnp.asarray(
        np.concatenate([x1, y1, x1 + wh[..., :1], y1 + wh[..., 1:]], -1)
        .astype(np.float32)
    )
    gt_labels = jnp.asarray(
        rng.integers(0, 80, (batch, num_gt)).astype(np.int32)
    )
    gt_mask = jnp.asarray(
        np.arange(num_gt)[None, :] < rng.integers(1, num_gt, (batch, 1))
    )

    def build(params: dict[str, Any]) -> Callable[[], Any]:
        config = matching_lib.MatchingConfig(
            fused_pallas=params["impl"] == "pallas",
            pallas_tile_a=params.get("tile_a"),
        )

        def assign(boxes, labels, mask):
            return matching_lib.anchor_targets_compact_batched(
                anchors, boxes, labels, mask, config
            )

        compiled = jax.jit(assign).lower(gt_boxes, gt_labels, gt_mask).compile()
        return lambda: compiled(gt_boxes, gt_labels, gt_mask)

    return build


# ---------------------------------------------------------------------------
# Search drivers
# ---------------------------------------------------------------------------

_BUILDERS: dict[str, Callable[..., Callable]] = {
    "nms": _nms_builder,
    "focal": _focal_builder,
    "matching": _matching_builder,
}


def _runnable(params: dict[str, Any], have_mosaic: bool) -> bool:
    return params.get("impl") != "pallas" or have_mosaic


def search_op(
    op: str,
    batch: int = DEFAULT_BATCH,
    hw: tuple[int, int] = DEFAULT_HW,
    steps: int = DEFAULT_STEPS,
    include_semantic: bool = False,
    candidates: list[dict[str, Any]] | None = None,
) -> tuple[dict[str, Any], list[Trial]]:
    """Measure every candidate for ``op``; returns (winner_entry, trials).

    The winner entry is directly mergeable into the registry's
    ``entries[op]`` (the candidate dicts are constructed that way).  Only
    exact-semantics successful trials are eligible winners.
    """
    if candidates is None:
        candidates = cand_lib.candidates_for(
            op, **({"include_semantic": True} if op == "nms" and include_semantic else {})
        )
    have_mosaic = mosaic_available()
    builder = _BUILDERS[op](batch, hw)
    trials: list[Trial] = []
    with trace.span("tune_search", op=op, candidates=len(candidates)):
        for params in candidates:
            semantics = (
                "approx"
                if op == "nms" and params.get("pre_nms_size", 1000) != 1000
                else "exact"
            )
            if not _runnable(params, have_mosaic):
                trials.append(Trial(
                    op=op, params=params, ms_per_call=None, window_ms=[],
                    noise_pct=None, semantics=semantics, status="skipped",
                    error="pallas candidate skipped: no Mosaic (non-TPU backend)",
                ))
                continue
            trials.append(run_trial(op, params, builder, steps, semantics))
    eligible = [
        t for t in trials if t.status == "ok" and t.semantics == "exact"
    ]
    if not eligible:
        raise RuntimeError(
            f"search_op({op!r}): no successful exact-semantics trial "
            f"(statuses: {[t.status for t in trials]})"
        )
    winner = min(eligible, key=lambda t: t.ms_per_call)
    return dict(winner.params), trials


def search_batch(
    hw: tuple[int, int] = DEFAULT_HW,
    steps: int = DEFAULT_STEPS,
    sizes: tuple[int, ...] = cand_lib.BATCH_SIZES,
    nms_entry: dict[str, Any] | None = None,
) -> tuple[int, list[Trial]]:
    """Per-bucket batch-size axis: highest postprocess THROUGHPUT
    (imgs/s, not ms/batch) over the detect postprocess at each candidate
    batch.  ``nms_entry`` (the just-searched NMS winner, when given) pins
    the suppression backend so the batch axis measures the tuned kernel.

    NOTE: this measures the postprocess program only (no backbone) — on a
    chip, confirm the winner end-to-end with ``bench.py --mode eval``
    before committing it; the RUNBOOK section spells out the workflow.
    """
    entry = {"impl": "xla", **(nms_entry or {})}
    trials: list[Trial] = []
    with trace.span("tune_search", op="batch", candidates=len(sizes)):
        for b in sizes:
            builder = _nms_builder(b, hw)
            t = run_trial("batch", {"batch": b, **entry}, builder, steps)
            trials.append(t)
    ok = [t for t in trials if t.status == "ok"]
    if not ok:
        raise RuntimeError("search_batch: every candidate failed")
    # imgs/s = batch / (ms/1e3): maximize throughput, not per-call latency.
    winner = max(ok, key=lambda t: t.params["batch"] / t.ms_per_call)
    return int(winner.params["batch"]), trials


def compose_schedule(
    device_kind: str,
    entries: dict[str, dict[str, Any]],
    trials: list[Trial],
) -> dict[str, Any]:
    """Winner entries + trial records → a schema-valid registry artifact
    (validated here, so a buggy search can never write a poisoned one)."""
    doc = {
        "format": schedule_lib.FORMAT,
        "device_kind": device_kind,
        "entries": entries,
        "trials": [t.record() for t in trials],
    }
    schedule_lib.validate_schedule(doc)
    return doc


def run_search(
    ops: tuple[str, ...] = ("nms", "focal", "matching"),
    batch: int = DEFAULT_BATCH,
    hw: tuple[int, int] = DEFAULT_HW,
    steps: int = DEFAULT_STEPS,
    include_semantic: bool = False,
    search_batches: bool = False,
    device_kind: str | None = None,
) -> dict[str, Any]:
    """The full search: every requested op, winners composed into one
    artifact document (NOT yet saved — the CLI owns persistence so a dry
    run can print without writing)."""
    if device_kind is None:
        device_kind = jax.devices()[0].device_kind
    entries: dict[str, dict[str, Any]] = {}
    all_trials: list[Trial] = []
    nms_entry: dict[str, Any] | None = None
    for op in ops:
        winner, trials = search_op(
            op, batch=batch, hw=hw, steps=steps,
            include_semantic=include_semantic,
        )
        entries[op] = winner
        all_trials.extend(trials)
        if op == "nms":
            nms_entry = winner
    if search_batches:
        best, trials = search_batch(hw=hw, steps=steps, nms_entry=nms_entry)
        all_trials.extend(trials)
        bucket = f"{hw[0]}x{hw[1]}"
        entries["eval"] = {"batch": {bucket: best}}
        # Serve also exports batch 1 so a lone straggler request never
        # pays a full winner-wide pad (serve/engine.batch_size_for).
        entries["serve"] = {
            "batch_sizes": {bucket: sorted({1, best})}
        }
    return compose_schedule(device_kind, entries, all_trials)
