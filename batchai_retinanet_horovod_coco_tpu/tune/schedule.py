"""Per-device schedule registry: schema-validated JSON, loud fallback.

One artifact per ``device_kind`` under ``artifacts/schedules/`` (e.g.
``tpu_v5_lite.json``), written by the search harness (tune/search.py) and
read by every schedule consumer.  The contract:

- **Schema-validated at load** (:func:`validate_schedule`): a committed
  artifact that drifts from the schema fails loudly at ``load_schedule``
  with every problem named — a malformed winner must never silently
  deoptimize (or semantically change) a consumer.
- **Unknown device_kind falls back to today's defaults with ONE loud
  structured event** (:func:`lookup`): a JSON line on stderr naming the
  device and the reason, once per (device, reason) per process — never a
  crash, because an untuned device must still train/serve at the
  hand-picked defaults every consumer shipped with before ISSUE 6.
- **Partial schedules deep-merge over the defaults**: an artifact may
  record only the ops it searched.

This module is import-light (stdlib + obs-free) so jax-free processes —
the shm decode workers transitively import config modules — can always
import consumers that import it.
"""

from __future__ import annotations

import copy
import json
import os
import re
import sys
from typing import Any

FORMAT = "retinanet.schedule.v1"

# Today's hand-picked defaults, exactly as the consumers hardcoded them
# before ISSUE 6 (ops/pallas/{focal,matching,nms}.py constants,
# DetectConfig/serve defaults).  ``impl: "auto"`` preserves a consumer's
# backend-conditional dispatch (matching: fused Pallas on TPU only).
DEFAULT_SCHEDULE: dict[str, Any] = {
    "nms": {"impl": "xla", "block_k": 256, "pre_nms_size": 1000},
    "focal": {"impl": "xla", "fwd_tile_a": 8192, "bwd_tile_a": 4096},
    "matching": {"impl": "auto", "tile_a": 8192},
    # Per-bucket batch sizes ("HxW" -> int for eval/train consumers,
    # "HxW" -> [int, ...] for the serve engine's executable table).
    "eval": {"batch": {}},
    "serve": {"batch_sizes": {}},
}

_IMPLS = {"xla", "pallas", "auto"}
_BUCKET_RE = re.compile(r"^\d+x\d+$")


class ScheduleError(ValueError):
    """A schedule artifact violates the schema (every problem listed)."""


def _check_tile(problems: list[str], op: str, key: str, value: Any) -> None:
    if not isinstance(value, int) or value <= 0 or value % 128 != 0:
        problems.append(
            f"{op}.{key}: must be a positive multiple of 128, got {value!r}"
        )


def validate_schedule(doc: Any) -> dict:
    """Validate a schedule document; returns it, or raises ScheduleError
    naming EVERY problem (not just the first)."""
    problems: list[str] = []
    if not isinstance(doc, dict):
        raise ScheduleError(f"schedule must be a JSON object, got {type(doc).__name__}")
    if doc.get("format") != FORMAT:
        problems.append(
            f"format: expected {FORMAT!r}, got {doc.get('format')!r}"
        )
    kind = doc.get("device_kind")
    if not isinstance(kind, str) or not kind:
        problems.append(f"device_kind: non-empty string required, got {kind!r}")
    entries = doc.get("entries")
    if not isinstance(entries, dict):
        problems.append(f"entries: object required, got {type(entries).__name__}")
        entries = {}
    unknown = sorted(set(entries) - set(DEFAULT_SCHEDULE))
    if unknown:
        problems.append(
            f"entries: unknown op keys {unknown} (known: "
            f"{sorted(DEFAULT_SCHEDULE)})"
        )
    for op in ("nms", "focal", "matching"):
        e = entries.get(op)
        if e is None:
            continue
        if not isinstance(e, dict):
            problems.append(f"{op}: object required")
            continue
        bad = sorted(set(e) - set(DEFAULT_SCHEDULE[op]))
        if bad:
            problems.append(f"{op}: unknown keys {bad}")
        impl = e.get("impl")
        if impl is not None and impl not in _IMPLS:
            problems.append(f"{op}.impl: must be one of {sorted(_IMPLS)}, got {impl!r}")
        for key in ("block_k", "fwd_tile_a", "bwd_tile_a", "tile_a"):
            if key in e:
                _check_tile(problems, op, key, e[key])
        if "pre_nms_size" in e:
            v = e["pre_nms_size"]
            if not isinstance(v, int) or not (1 <= v <= 100_000):
                problems.append(
                    f"nms.pre_nms_size: int in [1, 100000] required, got {v!r}"
                )
    for op, key, want_list in (("eval", "batch", False), ("serve", "batch_sizes", True)):
        e = entries.get(op)
        if e is None:
            continue
        if not isinstance(e, dict) or set(e) - {key}:
            problems.append(f"{op}: object with only {key!r} allowed")
            continue
        table = e.get(key, {})
        if not isinstance(table, dict):
            problems.append(f"{op}.{key}: object required")
            continue
        for bucket, v in table.items():
            if not _BUCKET_RE.match(str(bucket)):
                problems.append(f"{op}.{key}: bucket key {bucket!r} is not HxW")
            if want_list:
                ok = (
                    isinstance(v, list) and v
                    and all(isinstance(b, int) and b > 0 for b in v)
                )
                if not ok:
                    problems.append(
                        f"{op}.{key}[{bucket}]: non-empty list of positive "
                        f"ints required, got {v!r}"
                    )
            elif not isinstance(v, int) or v <= 0:
                problems.append(
                    f"{op}.{key}[{bucket}]: positive int required, got {v!r}"
                )
    if "trials" in doc and not isinstance(doc["trials"], list):
        problems.append("trials: list required when present")
    if problems:
        raise ScheduleError(
            "invalid schedule artifact:\n  - " + "\n  - ".join(problems)
        )
    return doc


def device_slug(device_kind: str) -> str:
    """'TPU v5 lite' → 'tpu_v5_lite' (artifact filename stem)."""
    return re.sub(r"[^a-z0-9]+", "_", device_kind.lower()).strip("_") or "unknown"


def schedule_dir(root: str | None = None) -> str:
    """artifacts/schedules/ under the repo root (or ``root``;
    ``RETINANET_SCHEDULE_DIR`` overrides for tests/deployments)."""
    if root is not None:
        return root
    env = os.environ.get("RETINANET_SCHEDULE_DIR")
    if env:
        return env
    repo = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    return os.path.join(repo, "artifacts", "schedules")


def schedule_path(device_kind: str, root: str | None = None) -> str:
    return os.path.join(schedule_dir(root), f"{device_slug(device_kind)}.json")


def save_schedule(doc: dict, root: str | None = None) -> str:
    """Validate + write one device's schedule artifact; returns the path.
    Atomic: every train/eval/serve/export bring-up resolves this file by
    path — a torn registry must be unobservable."""
    from batchai_retinanet_horovod_coco_tpu.utils.atomicio import (
        atomic_write_text,
    )

    validate_schedule(doc)
    path = schedule_path(doc["device_kind"], root)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    atomic_write_text(
        path, json.dumps(doc, indent=2, sort_keys=True) + "\n"
    )
    _cache_clear()
    return path


def load_schedule(path: str) -> dict:
    """Read + schema-validate one artifact; raises on any violation."""
    with open(path) as f:
        return validate_schedule(json.load(f))


def _merged(entries: dict) -> dict:
    out = copy.deepcopy(DEFAULT_SCHEDULE)
    for op, e in entries.items():
        out[op].update(e)
    return out


def _resolve_device_kind(device_kind: str | None) -> str:
    if device_kind is not None:
        return device_kind
    # Only read jax if something else already imported it — a config
    # lookup must never force a backend init (events.py's discipline).
    jax = sys.modules.get("jax")
    if jax is None:
        return "unknown"
    try:
        return jax.devices()[0].device_kind
    except Exception:
        return "unknown"


# One loud event per (device, reason-class) per process, not per lookup:
# the train loop resolves the schedule once per bucket compile and a
# thousand identical warnings would bury the one that matters.
_warned: set[tuple[str, str]] = set()
_cache: dict[str, tuple[dict, str]] = {}


def _cache_clear() -> None:
    _cache.clear()


def _emit_fallback(device_kind: str, reason: str, detail: str) -> None:
    key = (device_kind, reason)
    if key in _warned:
        return
    _warned.add(key)
    print(
        json.dumps(
            {
                "event": "schedule_fallback",
                "device_kind": device_kind,
                "reason": reason,
                "detail": detail[:500],
                "using": "built-in defaults",
            }
        ),
        file=sys.stderr,
        flush=True,
    )


def lookup(
    device_kind: str | None = None, root: str | None = None
) -> dict[str, Any]:
    """The consumer entrypoint: merged schedule entries for this device.

    Returns ``DEFAULT_SCHEDULE`` deep-merged with the device's committed
    artifact when one exists and validates; otherwise the defaults, with
    one structured ``schedule_fallback`` event on stderr per process
    (missing artifact OR invalid artifact — an implicit lookup must never
    crash a training/serving run; use :func:`load_schedule` for strict
    reads).  Results are cached per device_kind for the process lifetime
    — schedules are immutable once committed, and a stable resolution is
    what guarantees zero request-time recompiles in serve.
    """
    kind = _resolve_device_kind(device_kind)
    path = schedule_path(kind, root)
    # The resolved PATH is the cache key: it folds in root AND the
    # RETINANET_SCHEDULE_DIR env override, so a test (or a redeploy) that
    # repoints the registry dir can never be served another dir's entry.
    cache_key = path
    hit = _cache.get(cache_key)
    if hit is not None:
        return copy.deepcopy(hit[0])
    if not os.path.exists(path):
        _emit_fallback(kind, "no_schedule_artifact", path)
        merged = _merged({})
    else:
        try:
            merged = _merged(load_schedule(path)["entries"])
        except (ScheduleError, OSError, ValueError) as e:
            _emit_fallback(kind, "invalid_schedule_artifact", f"{path}: {e}")
            merged = _merged({})
    _cache[cache_key] = (merged, path)
    return copy.deepcopy(merged)


def eval_batch_for(
    hw: tuple[int, int],
    default: int,
    device_kind: str | None = None,
    root: str | None = None,
) -> int:
    """Per-bucket eval batch size from the device's schedule (bench
    ``--mode eval``'s consumer); ``default`` when the bucket is untuned."""
    table = lookup(device_kind, root)["eval"]["batch"]
    return int(table.get(f"{hw[0]}x{hw[1]}", default))


def serve_batch_sizes_for(
    hw: tuple[int, int],
    default: tuple[int, ...],
    device_kind: str | None = None,
    root: str | None = None,
) -> tuple[int, ...]:
    """Per-bucket serve executable batch sizes (DetectEngine.from_state's
    consumer); ``default`` when the bucket is untuned."""
    table = lookup(device_kind, root)["serve"]["batch_sizes"]
    sizes = table.get(f"{hw[0]}x{hw[1]}")
    return tuple(int(b) for b in sizes) if sizes else tuple(default)


def provenance(
    device_kind: str | None = None, root: str | None = None
) -> dict[str, Any]:
    """Where this device's schedule came from (for bench/manifest records):
    ``{"device_kind", "source" (path or "defaults"), "found"}``."""
    kind = _resolve_device_kind(device_kind)
    path = schedule_path(kind, root)
    found = False
    if os.path.exists(path):
        try:
            load_schedule(path)
            found = True
        except (ScheduleError, OSError, ValueError):
            found = False
    repo = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    if found and os.path.abspath(path).startswith(repo + os.sep):
        # Repo-relative in committed records (manifests, BENCH lines):
        # an absolute sandbox path says nothing to the next machine.
        path = os.path.relpath(path, repo)
    return {
        "device_kind": kind,
        "source": path if found else "defaults",
        "found": found,
    }
