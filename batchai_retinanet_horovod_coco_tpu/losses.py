"""Detection losses: focal classification loss + smooth-L1 box regression.

Capability parity with keras-retinanet ``losses.py`` (SURVEY.md M4):
- focal loss with alpha=0.25, gamma=2.0, computed on sigmoid logits over all
  non-ignored anchors;
- smooth-L1 with sigma=3 (beta = 1/sigma^2) on positive anchors only.

Normalization — DELIBERATE divergence from keras-retinanet: the reference
divides the batch-wide loss sum by the batch-wide positive count; we
normalize by the PER-IMAGE positive count (min 1) and then average over the
batch.  This (a) matches the RetinaNet paper's definition ("the total focal
loss of an image, normalized by the number of anchors assigned to
ground-truth boxes"), and (b) is exactly invariant under data-parallel
sharding: mean-over-images equals pmean of per-shard means regardless of how
positives distribute across shards, so the sharded step is bitwise-comparable
to the single-device step (tests/distributed/test_train_step.py).  The
reference's batch-global normalizer is NOT DP-invariant.

TPU-first differences from the reference:
- Losses consume the fixed-shape targets produced on device by
  ``ops.matching`` (the reference computed targets on the host loader thread
  and shipped them with the batch).  The train step uses the compact
  integer-label form (``total_loss_compact``/``focal_loss_compact``) so the
  (A, K) one-hot never hits HBM; the dense ``total_loss`` surface remains for
  tests/tools.
- Everything is expressed on logits (numerically stable
  log-sigmoid formulation), in the computation dtype of the model (bf16-safe:
  reductions accumulate in f32).
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import nn

from batchai_retinanet_horovod_coco_tpu.ops import matching


def _normalize_per_image(
    per_image: jnp.ndarray, anchor_state: jnp.ndarray
) -> jnp.ndarray:
    """Mean over images of per_image / max(#positive anchors, 1).

    The DP-invariant normalization described in the module docstring — the
    single definition shared by every loss path.
    """
    num_pos = jnp.sum(
        (anchor_state == matching.POSITIVE).astype(jnp.float32), axis=-1
    )
    return jnp.mean(per_image / jnp.maximum(num_pos, 1.0))


@dataclasses.dataclass(frozen=True)
class LossConfig:
    focal_alpha: float = 0.25
    focal_gamma: float = 2.0
    smooth_l1_beta: float = 1.0 / 9.0  # sigma=3 in the reference parametrization
    box_loss_weight: float = 1.0
    # Fused Pallas focal kernel (ops/pallas/focal.py).  None = resolved
    # from the per-device schedule registry by the train step
    # (train/step.py resolve_kernel_schedule; the built-in default is the
    # XLA path) and treated as OFF by direct loss calls.  The hand kernel
    # measured ~2.8x SLOWER than XLA's lowering of the exp-form jnp path
    # at K=80 on v5e (3.6 vs 7.9 ms fwd; the K=80 minor dim wastes 37% of
    # the 128-lane VPU tiles), so only a measured schedule winner — or an
    # explicit True — turns it on.  It stays bit-validated for K>=128
    # workloads.
    pallas_focal: bool | None = None
    # Run the Pallas kernel in interpreter mode (CPU tests of the wiring).
    pallas_interpret: bool = False
    # Anchor-tile widths for the fused kernel: None = the schedule-resolved
    # or module defaults (ops/pallas/focal.FWD_TILE_A/BWD_TILE_A).
    # Searched schedule parameters (tune/candidates.py).
    focal_fwd_tile_a: int | None = None
    focal_bwd_tile_a: int | None = None


def _focal_elementwise(
    logits: jnp.ndarray, targets: jnp.ndarray, config: LossConfig
) -> jnp.ndarray:
    """Per-element focal terms (same shape as ``logits``); f32 in/out.

    Exponential form — 2 transcendentals/element instead of ~5.  With
    sp_neg = softplus(-x) = -log p and sp_neg + x*t ∈ {sp_neg, softplus(x)}:
      bce        = -log p_t       = softplus(x) - x*t  (= sp_neg + x - x*t)
      (1-p_t)^γ  = exp(γ log(1-p_t)) = exp(-γ (sp_neg + x*t))
    Both factors come from ONE softplus and ONE exp; the VPU-bound focal
    op is transcendental-limited, so this halves its step cost (measured
    ~6.2ms → see ops/pallas/focal.py for the numbers at the flagship bucket).
    """
    sp_neg = nn.softplus(-logits)
    xt = logits * targets
    bce = sp_neg + logits - xt  # == softplus(x) - x*t, stable for any x
    modulator = jnp.exp(-config.focal_gamma * (sp_neg + xt))
    alpha_t = config.focal_alpha * targets + (1.0 - config.focal_alpha) * (
        1.0 - targets
    )
    return alpha_t * modulator * bce


def focal_sums(
    cls_logits: jnp.ndarray,
    cls_targets: jnp.ndarray,
    anchor_state: jnp.ndarray,
    config: LossConfig = LossConfig(),
) -> jnp.ndarray:
    """Per-image focal sums (...,) over non-ignored anchors — no normalizer.

    The additive core shared by :func:`focal_loss` and the per-level path
    (:func:`total_loss_compact_levels`): sums over different anchor subsets
    simply add.
    """
    logits = cls_logits.astype(jnp.float32)
    targets = cls_targets.astype(jnp.float32)
    loss = _focal_elementwise(logits, targets, config)  # (..., A, K)

    not_ignored = (anchor_state != matching.IGNORE).astype(jnp.float32)
    loss = loss * not_ignored[..., None]
    return jnp.sum(loss, axis=(-2, -1))


def focal_loss(
    cls_logits: jnp.ndarray,
    cls_targets: jnp.ndarray,
    anchor_state: jnp.ndarray,
    config: LossConfig = LossConfig(),
) -> jnp.ndarray:
    """Scalar focal loss.

    Args:
      cls_logits: (..., A, K) raw logits.
      cls_targets: (..., A, K) one-hot targets (all-zero rows for negatives).
      anchor_state: (..., A) in {-1 ignore, 0 negative, 1 positive}.
    """
    # Per-image normalization then batch mean (paper semantics, DP-invariant;
    # deliberate divergence from keras-retinanet — see module docstring).
    return _normalize_per_image(
        focal_sums(cls_logits, cls_targets, anchor_state, config), anchor_state
    )


def focal_loss_compact(
    cls_logits: jnp.ndarray,
    matched_labels: jnp.ndarray,
    anchor_state: jnp.ndarray,
    config: LossConfig = LossConfig(),
) -> jnp.ndarray:
    """Focal loss from integer labels — no dense one-hot target tensor.

    Mathematically identical to :func:`focal_loss` with
    ``cls_targets = one_hot(matched_labels) * (state == POSITIVE)``, but the
    one-hot is an implicit ``labels == iota(K)`` compare that XLA fuses into
    the elementwise focal computation.  At the flagship bucket this removes a
    (B, 201600, 80) f32 target tensor (~0.5 GB of HBM writes+reads per step)
    from the hot path — the train step consumes this form.

    Args:
      cls_logits: (..., A, K) raw logits.
      matched_labels: (..., A) int32 matched class ids (only read where
        positive).
      anchor_state: (..., A) in {-1 ignore, 0 negative, 1 positive}.
    """
    if config.pallas_focal:
        from batchai_retinanet_horovod_coco_tpu.ops.pallas import (
            focal_loss_per_image_sums,
        )

        # The kernel is written for (B, A, K); flatten any leading dims into
        # B (and add one for unbatched input) to honor the (..., A, K)
        # contract of this function.
        a, k = cls_logits.shape[-2:]
        sums = focal_loss_per_image_sums(
            cls_logits.reshape(-1, a, k),
            matched_labels.astype(jnp.int32).reshape(-1, a),
            anchor_state.astype(jnp.int32).reshape(-1, a),
            config.focal_alpha,
            config.focal_gamma,
            config.pallas_interpret,
            config.focal_fwd_tile_a,
            config.focal_bwd_tile_a,
        )
        return _normalize_per_image(
            sums.reshape(anchor_state.shape[:-1]), anchor_state
        )

    return _normalize_per_image(
        focal_sums_compact(cls_logits, matched_labels, anchor_state, config),
        anchor_state,
    )


def focal_sums_compact(
    cls_logits: jnp.ndarray,
    matched_labels: jnp.ndarray,
    anchor_state: jnp.ndarray,
    config: LossConfig = LossConfig(),
) -> jnp.ndarray:
    """Per-image focal sums from integer labels (implicit one-hot)."""
    num_classes = cls_logits.shape[-1]
    targets = (
        (anchor_state == matching.POSITIVE)[..., None]
        & (
            matched_labels[..., None]
            == jnp.arange(num_classes, dtype=jnp.int32)
        )
    ).astype(jnp.float32)
    return focal_sums(cls_logits, targets, anchor_state, config)


def _smooth_l1_elementwise(
    preds: jnp.ndarray, targets: jnp.ndarray, config: LossConfig
) -> jnp.ndarray:
    """Per-element smooth-L1 terms (f32 in/out) — the single definition
    shared by the anchor-major and NHWC paths."""
    diff = jnp.abs(preds - targets)
    beta = config.smooth_l1_beta
    return jnp.where(diff < beta, 0.5 * diff * diff / beta, diff - 0.5 * beta)


def smooth_l1_sums(
    box_preds: jnp.ndarray,
    box_targets: jnp.ndarray,
    anchor_state: jnp.ndarray,
    config: LossConfig = LossConfig(),
) -> jnp.ndarray:
    """Per-image smooth-L1 sums (...,) over positive anchors — no normalizer."""
    loss = _smooth_l1_elementwise(
        box_preds.astype(jnp.float32), box_targets.astype(jnp.float32), config
    )
    positive = (anchor_state == matching.POSITIVE).astype(jnp.float32)
    loss = loss * positive[..., None]
    return jnp.sum(loss, axis=(-2, -1))


def smooth_l1_loss(
    box_preds: jnp.ndarray,
    box_targets: jnp.ndarray,
    anchor_state: jnp.ndarray,
    config: LossConfig = LossConfig(),
) -> jnp.ndarray:
    """Scalar smooth-L1 regression loss over positive anchors.

    Args:
      box_preds: (..., A, 4) predicted deltas.
      box_targets: (..., A, 4) encoded target deltas.
      anchor_state: (..., A).
    """
    # Per-image normalization, then batch mean (see focal_loss).
    return _normalize_per_image(
        smooth_l1_sums(box_preds, box_targets, anchor_state, config),
        anchor_state,
    )


def total_loss_compact_levels(
    cls_levels: tuple[jnp.ndarray, ...],
    box_levels: tuple[jnp.ndarray, ...],
    matched_labels: jnp.ndarray,
    box_targets: jnp.ndarray,
    anchor_state: jnp.ndarray,
    config: LossConfig = LossConfig(),
) -> dict[str, jnp.ndarray]:
    """:func:`total_loss_compact` on PER-LEVEL head outputs.

    Consumes the raw per-pyramid-level (B, A_l, K)/(B, A_l, 4) head outputs
    instead of their concatenation, slicing the (cheap, (B, A)-shaped)
    targets to match.  Per-image sums add across levels; normalization
    happens once at the end, so the result equals :func:`total_loss_compact`
    on the concatenated outputs up to f32 summation order.

    MEASURED (v5e-1, flagship bucket): the step is ~1.3% SLOWER this way
    (57.7 vs 58.4 imgs/s) — XLA already folds the concat/split into
    adjacent fusions, and five per-level loss kernel groups (P6/P7 are
    tiny) cost more than the one fused pass.  The train step therefore
    keeps the concatenated form; this entrypoint stays for workloads with
    fewer/larger levels and as the consumer of a future NHWC-direct head
    output.
    """
    if config.pallas_focal:
        raise ValueError(
            "pallas_focal is not routed through the per-level path; use "
            "total_loss_compact (concatenated) with it"
        )
    covered = sum(c.shape[-2] for c in cls_levels)
    if covered != anchor_state.shape[-1]:
        # Checked BEFORE slicing: Python slices clamp, so over-coverage
        # would otherwise surface as an opaque broadcast error mid-loop.
        raise ValueError(
            f"level outputs cover {covered} anchors, targets have "
            f"{anchor_state.shape[-1]}"
        )
    cls_sum = jnp.zeros(anchor_state.shape[:-1], jnp.float32)
    box_sum = jnp.zeros(anchor_state.shape[:-1], jnp.float32)
    offset = 0
    for cls_l, box_l in zip(cls_levels, box_levels, strict=True):
        num = cls_l.shape[-2]
        sl = slice(offset, offset + num)
        offset += num
        cls_sum = cls_sum + focal_sums_compact(
            cls_l, matched_labels[..., sl], anchor_state[..., sl], config
        )
        box_sum = box_sum + smooth_l1_sums(
            box_l, box_targets[..., sl, :], anchor_state[..., sl], config
        )
    cls = _normalize_per_image(cls_sum, anchor_state)
    box = _normalize_per_image(box_sum, anchor_state)
    return {
        "loss": cls + config.box_loss_weight * box,
        "cls_loss": cls,
        "box_loss": box,
    }


def _focal_nhwc_elementwise(
    logits: jnp.ndarray, t_ck: jnp.ndarray, alpha: float, gamma: float
) -> jnp.ndarray:
    """Per-element focal terms from f32 logits and a BOOL target mask."""
    sp_neg = nn.softplus(-logits)
    xt = jnp.where(t_ck, logits, 0.0)
    bce = sp_neg + logits - xt
    modulator = jnp.exp(-gamma * (sp_neg + xt))
    alpha_t = jnp.where(t_ck, alpha, 1.0 - alpha)
    return alpha_t * modulator * bce


def _nhwc_masks(
    labels4: jnp.ndarray,
    state4: jnp.ndarray,
    k: int,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(t_ck, ni_ck) bool masks in the (B, h, w, A*K) channel layout.

    The A → A·K broadcast runs as ONE tiny matmul on the MXU: targets are
    encoded per anchor as e = label (positive) / k (negative) / k+1
    (ignore) — with k <= 255 every value is <= 256, so bf16 is exact, and
    each output column picks exactly one input (no accumulation) — and
    e @ R with the static 0/1 replication matrix R lands e in the
    (B, h, w, A·K) lane layout.
    The obvious broadcast-reshape forms all materialize worse: XLA cannot
    bitcast a (B, h, w, A, K)-broadcast into the 4-D lane tiling, so it
    materialized the compare's operand at full size (387 MB s32 per
    P3-sized level); measured per round-3 microbench (fwd+bwd focal sums,
    flagship shapes): 5-D reshape 4.6 ms, static-take 4.2 ms, this 2.7 ms.
    """
    lead = labels4.shape[:-1]
    a_loc = labels4.shape[-1]
    ck = a_loc * k
    if k > 255:
        # bf16 represents integers exactly only up to 256; fall back to the
        # broadcast-reshape form for very wide class counts.
        positive4 = state4 == matching.POSITIVE
        t_ck = (
            positive4[..., None]
            & (labels4[..., None] == jnp.arange(k, dtype=jnp.int32))
        ).reshape(*lead, ck)
        ni_ck = jnp.broadcast_to(
            (state4 != matching.IGNORE)[..., None], (*lead, a_loc, k)
        ).reshape(*lead, ck)
        return t_ck, ni_ck
    return _masks_from_encode(_nhwc_encode(labels4, state4, k), k)


def _nhwc_encode(
    labels4: jnp.ndarray, state4: jnp.ndarray, k: int
) -> jnp.ndarray:
    """The encoded-target matmul broadcast: (B, h, w, A) → (B, h, w, A·K)
    bf16 ``e`` with e = label / k (negative) / k+1 (ignore).  Requires
    k <= 255 (see _nhwc_masks)."""
    lead = labels4.shape[:-1]
    a_loc = labels4.shape[-1]
    ck = a_loc * k
    neg, ign = float(k), float(k + 1)  # sentinels outside the label range
    rep = np.zeros((a_loc, ck), np.float32)
    for a in range(a_loc):
        rep[a, a * k : (a + 1) * k] = 1.0
    rep = jnp.asarray(rep, dtype=jnp.bfloat16)
    e = jnp.where(
        state4 == matching.POSITIVE,
        labels4.astype(jnp.float32),
        jnp.where(state4 == matching.IGNORE, ign, neg),
    )
    return (e.astype(jnp.bfloat16).reshape(-1, a_loc) @ rep).reshape(*lead, ck)


def _masks_from_encode(
    e_ck: jnp.ndarray, k: int
) -> tuple[jnp.ndarray, jnp.ndarray]:
    k_idx = jnp.asarray(np.arange(e_ck.shape[-1]) % k, dtype=jnp.bfloat16)
    return e_ck == k_idx, e_ck != float(k + 1)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _focal_nhwc_level_sums(
    cls_l: jnp.ndarray,
    labels4: jnp.ndarray,
    state4: jnp.ndarray,
    alpha: float,
    gamma: float,
) -> jnp.ndarray:
    """Per-image focal sums for ONE level of raw (B, h, w, A*K) head output.

    ``labels4``/``state4`` are the (B, h, w, A) per-location targets.  The
    hand-written VJP is the point: JAX autodiff of the focal expression saves
    several full-size f32 residuals (softplus, modulator, bce — ~0.5 GB each
    at the flagship P3 level) for the backward pass, which made the loss
    slice HBM-bound (~6.4 ms fwd+bwd measured in isolation at the flagship
    bucket).  Here backward recomputes the cheap transcendentals from the
    saved bf16 logits in ONE fused pass whose only big output is d(logits) —
    measured 2.9 ms fwd+bwd for the same shapes, and bitwise-identical
    forward values (same expression graph).
    """
    t_ck, ni_ck = _nhwc_masks(labels4, state4, cls_l.shape[-1] // labels4.shape[-1])
    fl = _focal_nhwc_elementwise(cls_l.astype(jnp.float32), t_ck, alpha, gamma)
    return jnp.sum(jnp.where(ni_ck, fl, 0.0), axis=(-3, -2, -1))


def _focal_nhwc_level_sums_fwd(cls_l, labels4, state4, alpha, gamma):
    k = cls_l.shape[-1] // labels4.shape[-1]
    if k > 255:
        out = _focal_nhwc_level_sums(cls_l, labels4, state4, alpha, gamma)
        return out, (cls_l, labels4, state4, None)
    # Save the bf16 encoded-target tensor as the residual: backward reads
    # it instead of re-running the mask matmul (one 258 MB read vs
    # dot + write + read at the flagship bucket).
    e_ck = _nhwc_encode(labels4, state4, k)
    t_ck, ni_ck = _masks_from_encode(e_ck, k)
    fl = _focal_nhwc_elementwise(cls_l.astype(jnp.float32), t_ck, alpha, gamma)
    out = jnp.sum(jnp.where(ni_ck, fl, 0.0), axis=(-3, -2, -1))
    # state4 is NOT a residual on this path (backward only needs its shape,
    # == labels4's, for the float0 cotangent) — holding it would keep dead
    # bytes alive across the whole backbone backward.
    return out, (cls_l, labels4, None, e_ck)


def _focal_nhwc_level_sums_bwd(alpha, gamma, res, g):
    cls_l, labels4, state4, e_ck = res
    k = cls_l.shape[-1] // labels4.shape[-1]
    if e_ck is None:
        t_ck, ni_ck = _nhwc_masks(labels4, state4, k)
    else:
        t_ck, ni_ck = _masks_from_encode(e_ck, k)
    x = cls_l.astype(jnp.float32)
    # d f / d x in closed form, one fused elementwise pass:
    #   s = sigmoid(x), spn = softplus(-x), spp = softplus(x)
    #   t=0: f = (1-a)·exp(-g·spn)·spp  →  f' = (1-a)·exp(-g·spn)·(g(1-s)spp + s)
    #   t=1: f = a·exp(-g·spp)·spn      →  f' = -a·exp(-g·spp)·(g·s·spn + 1 - s)
    s = nn.sigmoid(x)
    spn = nn.softplus(-x)
    spp = spn + x  # == softplus(x), stable for any x
    d_neg = (1.0 - alpha) * jnp.exp(-gamma * spn) * (gamma * (1.0 - s) * spp + s)
    d_pos = -alpha * jnp.exp(-gamma * spp) * (gamma * s * spn + 1.0 - s)
    df = jnp.where(ni_ck, jnp.where(t_ck, d_pos, d_neg), 0.0)
    # g has the per-image shape (...,); broadcast over (h, w, ck).
    dcls = (g[..., None, None, None] * df).astype(cls_l.dtype)
    f0 = lambda a: np.zeros(a.shape, jax.dtypes.float0)  # int-array cotangents
    return dcls, f0(labels4), f0(labels4)  # state4 shares labels4's shape


_focal_nhwc_level_sums.defvjp(_focal_nhwc_level_sums_fwd, _focal_nhwc_level_sums_bwd)


def total_loss_compact_nhwc(
    cls_levels: tuple[jnp.ndarray, ...],
    box_levels: tuple[jnp.ndarray, ...],
    matched_labels: jnp.ndarray,
    box_targets: jnp.ndarray,
    anchor_state: jnp.ndarray,
    anchors_per_location: int,
    config: LossConfig = LossConfig(),
    planar_box_targets: bool = False,
) -> dict[str, jnp.ndarray]:
    """:func:`total_loss_compact` on RAW (B, h, w, A·K) head outputs.

    The anchor-major path retiles every level's lane dimension
    (A·K → K-minor), concatenates, and splits again in the backward pass —
    ~4 ms of pure layout traffic at the flagship bucket (round-3 profile:
    reshape.419/483 + concatenate.7 + split.1).  Here the big tensors stay
    in their conv-native layout end-to-end: the per-level target slices are
    the only retiled arrays ((B, A_l) int32/int8 — a few MB), and the view
    reshapes on the head outputs feed straight into the fused elementwise
    focal/smooth-L1 + reduction, so XLA never materializes them.  Equals
    :func:`total_loss_compact` on the concatenated outputs up to f32
    summation order (pinned by a unit test).
    """
    if config.pallas_focal:
        raise ValueError(
            "pallas_focal is not routed through the NHWC path; use "
            "total_loss_compact (concatenated) with it"
        )
    a_loc = anchors_per_location
    covered = sum(c.shape[1] * c.shape[2] * a_loc for c in cls_levels)
    if covered != anchor_state.shape[-1]:
        raise ValueError(
            f"level outputs cover {covered} anchors, targets have "
            f"{anchor_state.shape[-1]}"
        )
    batch_shape = anchor_state.shape[:-1]
    cls_sum = jnp.zeros(batch_shape, jnp.float32)
    box_sum = jnp.zeros(batch_shape, jnp.float32)
    offset = 0
    for cls_l, box_l in zip(cls_levels, box_levels, strict=True):
        b, h, w, ck = cls_l.shape
        k = ck // a_loc
        n = h * w * a_loc
        sl = slice(offset, offset + n)
        offset += n
        # Per-level targets, reshaped on the SMALL side only ((B, A_l)
        # ints and the (B, A_l, 4) box targets — a few MB).  The big head
        # tensors are never split into (A, K)/(A, 4) views: a 4-minor-dim
        # view of a (B, h, w, 36) tensor retiles it catastrophically
        # (measured: the first nhwc attempt moved ~7 ms of retile cost
        # INTO the loss).  Instead the masks/targets broadcast-reshape
        # from (B, h, w, A) up to the A·K channel layout (``_nhwc_masks``)
        # — bool through any materialization XLA decides on.  The focal
        # term uses the hand-VJP level kernel: autodiff residuals were
        # the dominant loss cost (see ``_focal_nhwc_level_sums``).
        labels4 = matched_labels[..., sl].reshape(*batch_shape, h, w, a_loc)
        state4 = anchor_state[..., sl].reshape(*batch_shape, h, w, a_loc)
        positive4 = state4 == matching.POSITIVE
        cls_sum = cls_sum + _focal_nhwc_level_sums(
            cls_l, labels4, state4, config.focal_alpha, config.focal_gamma
        )

        c4 = a_loc * 4
        if planar_box_targets:
            # (..., 4, A) planar targets: slice lanes, then one SMALL
            # transpose (a few MB, dense tiles) into the (a, j) channel
            # order of the head output.  The (..., A, 4) form instead
            # retiles a 32x-lane-padded tensor (~1 ms for P3 alone,
            # round-3 profile reshape.488).
            boxt_ck = (
                jnp.moveaxis(
                    box_targets[..., sl].reshape(
                        *batch_shape, 4, h, w, a_loc
                    ),
                    -4,
                    -1,
                )
                .reshape(*batch_shape, h, w, c4)
                .astype(jnp.float32)
            )
        else:
            boxt_ck = (
                box_targets[..., sl, :]
                .reshape(*batch_shape, h, w, c4)
                .astype(jnp.float32)
            )
        sl1 = _smooth_l1_elementwise(box_l.astype(jnp.float32), boxt_ck, config)
        pos_ck = jnp.broadcast_to(
            positive4[..., None], (*batch_shape, h, w, a_loc, 4)
        ).reshape(*batch_shape, h, w, c4)
        box_sum = box_sum + jnp.sum(
            jnp.where(pos_ck, sl1, 0.0), axis=(-3, -2, -1)
        )
    cls = _normalize_per_image(cls_sum, anchor_state)
    box = _normalize_per_image(box_sum, anchor_state)
    return {
        "loss": cls + config.box_loss_weight * box,
        "cls_loss": cls,
        "box_loss": box,
    }


def total_loss_compact(
    cls_logits: jnp.ndarray,
    box_preds: jnp.ndarray,
    matched_labels: jnp.ndarray,
    box_targets: jnp.ndarray,
    anchor_state: jnp.ndarray,
    config: LossConfig = LossConfig(),
) -> dict[str, jnp.ndarray]:
    """:func:`total_loss` on compact (integer-label) targets — the step path."""
    cls = focal_loss_compact(cls_logits, matched_labels, anchor_state, config)
    box = smooth_l1_loss(box_preds, box_targets, anchor_state, config)
    return {
        "loss": cls + config.box_loss_weight * box,
        "cls_loss": cls,
        "box_loss": box,
    }


def total_loss(
    cls_logits: jnp.ndarray,
    box_preds: jnp.ndarray,
    cls_targets: jnp.ndarray,
    box_targets: jnp.ndarray,
    anchor_state: jnp.ndarray,
    config: LossConfig = LossConfig(),
) -> dict[str, jnp.ndarray]:
    cls = focal_loss(cls_logits, cls_targets, anchor_state, config)
    box = smooth_l1_loss(box_preds, box_targets, anchor_state, config)
    return {
        "loss": cls + config.box_loss_weight * box,
        "cls_loss": cls,
        "box_loss": box,
    }
