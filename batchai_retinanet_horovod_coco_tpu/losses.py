"""Detection losses: focal classification loss + smooth-L1 box regression.

Capability parity with keras-retinanet ``losses.py`` (SURVEY.md M4):
- focal loss with alpha=0.25, gamma=2.0, computed on sigmoid logits over all
  non-ignored anchors;
- smooth-L1 with sigma=3 (beta = 1/sigma^2) on positive anchors only.

Normalization — DELIBERATE divergence from keras-retinanet: the reference
divides the batch-wide loss sum by the batch-wide positive count; we
normalize by the PER-IMAGE positive count (min 1) and then average over the
batch.  This (a) matches the RetinaNet paper's definition ("the total focal
loss of an image, normalized by the number of anchors assigned to
ground-truth boxes"), and (b) is exactly invariant under data-parallel
sharding: mean-over-images equals pmean of per-shard means regardless of how
positives distribute across shards, so the sharded step is bitwise-comparable
to the single-device step (tests/distributed/test_train_step.py).  The
reference's batch-global normalizer is NOT DP-invariant.

TPU-first differences from the reference:
- Losses consume the fixed-shape targets produced on device by
  ``ops.matching`` (the reference computed targets on the host loader thread
  and shipped them with the batch).  The train step uses the compact
  integer-label form (``total_loss_compact``/``focal_loss_compact``) so the
  (A, K) one-hot never hits HBM; the dense ``total_loss`` surface remains for
  tests/tools.
- Everything is expressed on logits (numerically stable
  log-sigmoid formulation), in the computation dtype of the model (bf16-safe:
  reductions accumulate in f32).
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
from jax import nn

from batchai_retinanet_horovod_coco_tpu.ops import matching


def _normalize_per_image(
    per_image: jnp.ndarray, anchor_state: jnp.ndarray
) -> jnp.ndarray:
    """Mean over images of per_image / max(#positive anchors, 1).

    The DP-invariant normalization described in the module docstring — the
    single definition shared by every loss path.
    """
    num_pos = jnp.sum(
        (anchor_state == matching.POSITIVE).astype(jnp.float32), axis=-1
    )
    return jnp.mean(per_image / jnp.maximum(num_pos, 1.0))


@dataclasses.dataclass(frozen=True)
class LossConfig:
    focal_alpha: float = 0.25
    focal_gamma: float = 2.0
    smooth_l1_beta: float = 1.0 / 9.0  # sigma=3 in the reference parametrization
    box_loss_weight: float = 1.0
    # Opt-in fused Pallas focal kernel (ops/pallas/focal.py).  Default OFF:
    # measured on v5e at the flagship bucket, XLA's lowering of the exp-form
    # jnp path below is ~2.8x faster than the hand kernel (3.6 vs 7.9 ms fwd;
    # the K=80 minor dim wastes 37% of the 128-lane VPU tiles in Pallas).
    # The kernel stays available (and bit-validated) for K>=128 workloads.
    pallas_focal: bool = False
    # Run the Pallas kernel in interpreter mode (CPU tests of the wiring).
    pallas_interpret: bool = False


def _focal_elementwise(
    logits: jnp.ndarray, targets: jnp.ndarray, config: LossConfig
) -> jnp.ndarray:
    """Per-element focal terms (same shape as ``logits``); f32 in/out.

    Exponential form — 2 transcendentals/element instead of ~5.  With
    sp_neg = softplus(-x) = -log p and sp_neg + x*t ∈ {sp_neg, softplus(x)}:
      bce        = -log p_t       = softplus(x) - x*t  (= sp_neg + x - x*t)
      (1-p_t)^γ  = exp(γ log(1-p_t)) = exp(-γ (sp_neg + x*t))
    Both factors come from ONE softplus and ONE exp; the VPU-bound focal
    op is transcendental-limited, so this halves its step cost (measured
    ~6.2ms → see ops/pallas/focal.py for the numbers at the flagship bucket).
    """
    sp_neg = nn.softplus(-logits)
    xt = logits * targets
    bce = sp_neg + logits - xt  # == softplus(x) - x*t, stable for any x
    modulator = jnp.exp(-config.focal_gamma * (sp_neg + xt))
    alpha_t = config.focal_alpha * targets + (1.0 - config.focal_alpha) * (
        1.0 - targets
    )
    return alpha_t * modulator * bce


def focal_sums(
    cls_logits: jnp.ndarray,
    cls_targets: jnp.ndarray,
    anchor_state: jnp.ndarray,
    config: LossConfig = LossConfig(),
) -> jnp.ndarray:
    """Per-image focal sums (...,) over non-ignored anchors — no normalizer.

    The additive core shared by :func:`focal_loss` and the per-level path
    (:func:`total_loss_compact_levels`): sums over different anchor subsets
    simply add.
    """
    logits = cls_logits.astype(jnp.float32)
    targets = cls_targets.astype(jnp.float32)
    loss = _focal_elementwise(logits, targets, config)  # (..., A, K)

    not_ignored = (anchor_state != matching.IGNORE).astype(jnp.float32)
    loss = loss * not_ignored[..., None]
    return jnp.sum(loss, axis=(-2, -1))


def focal_loss(
    cls_logits: jnp.ndarray,
    cls_targets: jnp.ndarray,
    anchor_state: jnp.ndarray,
    config: LossConfig = LossConfig(),
) -> jnp.ndarray:
    """Scalar focal loss.

    Args:
      cls_logits: (..., A, K) raw logits.
      cls_targets: (..., A, K) one-hot targets (all-zero rows for negatives).
      anchor_state: (..., A) in {-1 ignore, 0 negative, 1 positive}.
    """
    # Per-image normalization then batch mean (paper semantics, DP-invariant;
    # deliberate divergence from keras-retinanet — see module docstring).
    return _normalize_per_image(
        focal_sums(cls_logits, cls_targets, anchor_state, config), anchor_state
    )


def focal_loss_compact(
    cls_logits: jnp.ndarray,
    matched_labels: jnp.ndarray,
    anchor_state: jnp.ndarray,
    config: LossConfig = LossConfig(),
) -> jnp.ndarray:
    """Focal loss from integer labels — no dense one-hot target tensor.

    Mathematically identical to :func:`focal_loss` with
    ``cls_targets = one_hot(matched_labels) * (state == POSITIVE)``, but the
    one-hot is an implicit ``labels == iota(K)`` compare that XLA fuses into
    the elementwise focal computation.  At the flagship bucket this removes a
    (B, 201600, 80) f32 target tensor (~0.5 GB of HBM writes+reads per step)
    from the hot path — the train step consumes this form.

    Args:
      cls_logits: (..., A, K) raw logits.
      matched_labels: (..., A) int32 matched class ids (only read where
        positive).
      anchor_state: (..., A) in {-1 ignore, 0 negative, 1 positive}.
    """
    if config.pallas_focal:
        from batchai_retinanet_horovod_coco_tpu.ops.pallas import (
            focal_loss_per_image_sums,
        )

        # The kernel is written for (B, A, K); flatten any leading dims into
        # B (and add one for unbatched input) to honor the (..., A, K)
        # contract of this function.
        a, k = cls_logits.shape[-2:]
        sums = focal_loss_per_image_sums(
            cls_logits.reshape(-1, a, k),
            matched_labels.astype(jnp.int32).reshape(-1, a),
            anchor_state.astype(jnp.int32).reshape(-1, a),
            config.focal_alpha,
            config.focal_gamma,
            config.pallas_interpret,
        )
        return _normalize_per_image(
            sums.reshape(anchor_state.shape[:-1]), anchor_state
        )

    return _normalize_per_image(
        focal_sums_compact(cls_logits, matched_labels, anchor_state, config),
        anchor_state,
    )


def focal_sums_compact(
    cls_logits: jnp.ndarray,
    matched_labels: jnp.ndarray,
    anchor_state: jnp.ndarray,
    config: LossConfig = LossConfig(),
) -> jnp.ndarray:
    """Per-image focal sums from integer labels (implicit one-hot)."""
    num_classes = cls_logits.shape[-1]
    targets = (
        (anchor_state == matching.POSITIVE)[..., None]
        & (
            matched_labels[..., None]
            == jnp.arange(num_classes, dtype=jnp.int32)
        )
    ).astype(jnp.float32)
    return focal_sums(cls_logits, targets, anchor_state, config)


def _smooth_l1_elementwise(
    preds: jnp.ndarray, targets: jnp.ndarray, config: LossConfig
) -> jnp.ndarray:
    """Per-element smooth-L1 terms (f32 in/out) — the single definition
    shared by the anchor-major and NHWC paths."""
    diff = jnp.abs(preds - targets)
    beta = config.smooth_l1_beta
    return jnp.where(diff < beta, 0.5 * diff * diff / beta, diff - 0.5 * beta)


def smooth_l1_sums(
    box_preds: jnp.ndarray,
    box_targets: jnp.ndarray,
    anchor_state: jnp.ndarray,
    config: LossConfig = LossConfig(),
) -> jnp.ndarray:
    """Per-image smooth-L1 sums (...,) over positive anchors — no normalizer."""
    loss = _smooth_l1_elementwise(
        box_preds.astype(jnp.float32), box_targets.astype(jnp.float32), config
    )
    positive = (anchor_state == matching.POSITIVE).astype(jnp.float32)
    loss = loss * positive[..., None]
    return jnp.sum(loss, axis=(-2, -1))


def smooth_l1_loss(
    box_preds: jnp.ndarray,
    box_targets: jnp.ndarray,
    anchor_state: jnp.ndarray,
    config: LossConfig = LossConfig(),
) -> jnp.ndarray:
    """Scalar smooth-L1 regression loss over positive anchors.

    Args:
      box_preds: (..., A, 4) predicted deltas.
      box_targets: (..., A, 4) encoded target deltas.
      anchor_state: (..., A).
    """
    # Per-image normalization, then batch mean (see focal_loss).
    return _normalize_per_image(
        smooth_l1_sums(box_preds, box_targets, anchor_state, config),
        anchor_state,
    )


def total_loss_compact_levels(
    cls_levels: tuple[jnp.ndarray, ...],
    box_levels: tuple[jnp.ndarray, ...],
    matched_labels: jnp.ndarray,
    box_targets: jnp.ndarray,
    anchor_state: jnp.ndarray,
    config: LossConfig = LossConfig(),
) -> dict[str, jnp.ndarray]:
    """:func:`total_loss_compact` on PER-LEVEL head outputs.

    Consumes the raw per-pyramid-level (B, A_l, K)/(B, A_l, 4) head outputs
    instead of their concatenation, slicing the (cheap, (B, A)-shaped)
    targets to match.  Per-image sums add across levels; normalization
    happens once at the end, so the result equals :func:`total_loss_compact`
    on the concatenated outputs up to f32 summation order.

    MEASURED (v5e-1, flagship bucket): the step is ~1.3% SLOWER this way
    (57.7 vs 58.4 imgs/s) — XLA already folds the concat/split into
    adjacent fusions, and five per-level loss kernel groups (P6/P7 are
    tiny) cost more than the one fused pass.  The train step therefore
    keeps the concatenated form; this entrypoint stays for workloads with
    fewer/larger levels and as the consumer of a future NHWC-direct head
    output.
    """
    if config.pallas_focal:
        raise ValueError(
            "pallas_focal is not routed through the per-level path; use "
            "total_loss_compact (concatenated) with it"
        )
    covered = sum(c.shape[-2] for c in cls_levels)
    if covered != anchor_state.shape[-1]:
        # Checked BEFORE slicing: Python slices clamp, so over-coverage
        # would otherwise surface as an opaque broadcast error mid-loop.
        raise ValueError(
            f"level outputs cover {covered} anchors, targets have "
            f"{anchor_state.shape[-1]}"
        )
    cls_sum = jnp.zeros(anchor_state.shape[:-1], jnp.float32)
    box_sum = jnp.zeros(anchor_state.shape[:-1], jnp.float32)
    offset = 0
    for cls_l, box_l in zip(cls_levels, box_levels, strict=True):
        num = cls_l.shape[-2]
        sl = slice(offset, offset + num)
        offset += num
        cls_sum = cls_sum + focal_sums_compact(
            cls_l, matched_labels[..., sl], anchor_state[..., sl], config
        )
        box_sum = box_sum + smooth_l1_sums(
            box_l, box_targets[..., sl, :], anchor_state[..., sl], config
        )
    cls = _normalize_per_image(cls_sum, anchor_state)
    box = _normalize_per_image(box_sum, anchor_state)
    return {
        "loss": cls + config.box_loss_weight * box,
        "cls_loss": cls,
        "box_loss": box,
    }


def total_loss_compact_nhwc(
    cls_levels: tuple[jnp.ndarray, ...],
    box_levels: tuple[jnp.ndarray, ...],
    matched_labels: jnp.ndarray,
    box_targets: jnp.ndarray,
    anchor_state: jnp.ndarray,
    anchors_per_location: int,
    config: LossConfig = LossConfig(),
) -> dict[str, jnp.ndarray]:
    """:func:`total_loss_compact` on RAW (B, h, w, A·K) head outputs.

    The anchor-major path retiles every level's lane dimension
    (A·K → K-minor), concatenates, and splits again in the backward pass —
    ~4 ms of pure layout traffic at the flagship bucket (round-3 profile:
    reshape.419/483 + concatenate.7 + split.1).  Here the big tensors stay
    in their conv-native layout end-to-end: the per-level target slices are
    the only retiled arrays ((B, A_l) int32/int8 — a few MB), and the view
    reshapes on the head outputs feed straight into the fused elementwise
    focal/smooth-L1 + reduction, so XLA never materializes them.  Equals
    :func:`total_loss_compact` on the concatenated outputs up to f32
    summation order (pinned by a unit test).
    """
    if config.pallas_focal:
        raise ValueError(
            "pallas_focal is not routed through the NHWC path; use "
            "total_loss_compact (concatenated) with it"
        )
    a_loc = anchors_per_location
    covered = sum(c.shape[1] * c.shape[2] * a_loc for c in cls_levels)
    if covered != anchor_state.shape[-1]:
        raise ValueError(
            f"level outputs cover {covered} anchors, targets have "
            f"{anchor_state.shape[-1]}"
        )
    batch_shape = anchor_state.shape[:-1]
    cls_sum = jnp.zeros(batch_shape, jnp.float32)
    box_sum = jnp.zeros(batch_shape, jnp.float32)
    offset = 0
    for cls_l, box_l in zip(cls_levels, box_levels, strict=True):
        b, h, w, ck = cls_l.shape
        k = ck // a_loc
        n = h * w * a_loc
        sl = slice(offset, offset + n)
        offset += n
        # Per-level targets, reshaped on the SMALL side only ((B, A_l)
        # ints and the (B, A_l, 4) box targets — a few MB).  The big head
        # tensors are never split into (A, K)/(A, 4) views: a 4-minor-dim
        # view of a (B, h, w, 36) tensor retiles it catastrophically
        # (measured: the first nhwc attempt moved ~7 ms of retile cost
        # INTO the loss).  Instead the masks/targets broadcast-reshape
        # from (B, h, w, A) up to the A·K channel layout — index
        # arithmetic inside the fusion, no materialization.
        labels4 = matched_labels[..., sl].reshape(*batch_shape, h, w, a_loc)
        state4 = anchor_state[..., sl].reshape(*batch_shape, h, w, a_loc)
        positive4 = state4 == matching.POSITIVE

        # Masks stay BOOL through any materialization XLA decides on (the
        # broadcast-reshapes below are not bitcasts, so they can land in
        # HBM) — as f32 they measured ~4x the copy traffic.  The focal
        # arithmetic consumes the bool target via where-forms.
        t_ck = (
            positive4[..., None]
            & (labels4[..., None] == jnp.arange(k, dtype=jnp.int32))
        ).reshape(*batch_shape, h, w, ck)  # (B, h, w, A*K) bool
        logits = cls_l.astype(jnp.float32)
        sp_neg = nn.softplus(-logits)
        xt = jnp.where(t_ck, logits, 0.0)
        bce = sp_neg + logits - xt
        modulator = jnp.exp(-config.focal_gamma * (sp_neg + xt))
        alpha_t = jnp.where(t_ck, config.focal_alpha, 1.0 - config.focal_alpha)
        fl = alpha_t * modulator * bce
        ni_ck = jnp.broadcast_to(
            (state4 != matching.IGNORE)[..., None],
            (*batch_shape, h, w, a_loc, k),
        ).reshape(*batch_shape, h, w, ck)
        cls_sum = cls_sum + jnp.sum(
            jnp.where(ni_ck, fl, 0.0), axis=(-3, -2, -1)
        )

        c4 = a_loc * 4
        boxt_ck = (
            box_targets[..., sl, :]
            .reshape(*batch_shape, h, w, c4)
            .astype(jnp.float32)
        )
        sl1 = _smooth_l1_elementwise(box_l.astype(jnp.float32), boxt_ck, config)
        pos_ck = jnp.broadcast_to(
            positive4[..., None], (*batch_shape, h, w, a_loc, 4)
        ).reshape(*batch_shape, h, w, c4)
        box_sum = box_sum + jnp.sum(
            jnp.where(pos_ck, sl1, 0.0), axis=(-3, -2, -1)
        )
    cls = _normalize_per_image(cls_sum, anchor_state)
    box = _normalize_per_image(box_sum, anchor_state)
    return {
        "loss": cls + config.box_loss_weight * box,
        "cls_loss": cls,
        "box_loss": box,
    }


def total_loss_compact(
    cls_logits: jnp.ndarray,
    box_preds: jnp.ndarray,
    matched_labels: jnp.ndarray,
    box_targets: jnp.ndarray,
    anchor_state: jnp.ndarray,
    config: LossConfig = LossConfig(),
) -> dict[str, jnp.ndarray]:
    """:func:`total_loss` on compact (integer-label) targets — the step path."""
    cls = focal_loss_compact(cls_logits, matched_labels, anchor_state, config)
    box = smooth_l1_loss(box_preds, box_targets, anchor_state, config)
    return {
        "loss": cls + config.box_loss_weight * box,
        "cls_loss": cls,
        "box_loss": box,
    }


def total_loss(
    cls_logits: jnp.ndarray,
    box_preds: jnp.ndarray,
    cls_targets: jnp.ndarray,
    box_targets: jnp.ndarray,
    anchor_state: jnp.ndarray,
    config: LossConfig = LossConfig(),
) -> dict[str, jnp.ndarray]:
    cls = focal_loss(cls_logits, cls_targets, anchor_state, config)
    box = smooth_l1_loss(box_preds, box_targets, anchor_state, config)
    return {
        "loss": cls + config.box_loss_weight * box,
        "cls_loss": cls,
        "box_loss": box,
    }
