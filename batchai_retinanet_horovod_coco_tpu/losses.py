"""Detection losses: focal classification loss + smooth-L1 box regression.

Capability parity with keras-retinanet ``losses.py`` (SURVEY.md M4):
- focal loss with alpha=0.25, gamma=2.0, computed on sigmoid logits over all
  non-ignored anchors;
- smooth-L1 with sigma=3 (beta = 1/sigma^2) on positive anchors only.

Normalization — DELIBERATE divergence from keras-retinanet: the reference
divides the batch-wide loss sum by the batch-wide positive count; we
normalize by the PER-IMAGE positive count (min 1) and then average over the
batch.  This (a) matches the RetinaNet paper's definition ("the total focal
loss of an image, normalized by the number of anchors assigned to
ground-truth boxes"), and (b) is exactly invariant under data-parallel
sharding: mean-over-images equals pmean of per-shard means regardless of how
positives distribute across shards, so the sharded step is bitwise-comparable
to the single-device step (tests/distributed/test_train_step.py).  The
reference's batch-global normalizer is NOT DP-invariant.

TPU-first differences from the reference:
- Losses consume the fixed-shape targets produced on device by
  ``ops.matching`` (the reference computed targets on the host loader thread
  and shipped them with the batch).  The train step uses the compact
  integer-label form (``total_loss_compact``/``focal_loss_compact``) so the
  (A, K) one-hot never hits HBM; the dense ``total_loss`` surface remains for
  tests/tools.
- Everything is expressed on logits (numerically stable
  log-sigmoid formulation), in the computation dtype of the model (bf16-safe:
  reductions accumulate in f32).
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
from jax import nn

from batchai_retinanet_horovod_coco_tpu.ops import matching


@dataclasses.dataclass(frozen=True)
class LossConfig:
    focal_alpha: float = 0.25
    focal_gamma: float = 2.0
    smooth_l1_beta: float = 1.0 / 9.0  # sigma=3 in the reference parametrization
    box_loss_weight: float = 1.0


def focal_loss(
    cls_logits: jnp.ndarray,
    cls_targets: jnp.ndarray,
    anchor_state: jnp.ndarray,
    config: LossConfig = LossConfig(),
) -> jnp.ndarray:
    """Scalar focal loss.

    Args:
      cls_logits: (..., A, K) raw logits.
      cls_targets: (..., A, K) one-hot targets (all-zero rows for negatives).
      anchor_state: (..., A) in {-1 ignore, 0 negative, 1 positive}.
    """
    logits = cls_logits.astype(jnp.float32)
    targets = cls_targets.astype(jnp.float32)

    p = nn.sigmoid(logits)
    # Stable BCE from logits.
    bce = nn.softplus(logits) - logits * targets  # == -[t log p + (1-t) log(1-p)]
    p_t = p * targets + (1.0 - p) * (1.0 - targets)
    alpha_t = config.focal_alpha * targets + (1.0 - config.focal_alpha) * (
        1.0 - targets
    )
    loss = alpha_t * (1.0 - p_t) ** config.focal_gamma * bce  # (..., A, K)

    not_ignored = (anchor_state != matching.IGNORE).astype(jnp.float32)
    loss = loss * not_ignored[..., None]

    # Per-image normalization then batch mean (paper semantics, DP-invariant;
    # deliberate divergence from keras-retinanet — see module docstring).
    per_image = jnp.sum(loss, axis=(-2, -1))
    num_pos = jnp.sum(
        (anchor_state == matching.POSITIVE).astype(jnp.float32), axis=-1
    )
    return jnp.mean(per_image / jnp.maximum(num_pos, 1.0))


def focal_loss_compact(
    cls_logits: jnp.ndarray,
    matched_labels: jnp.ndarray,
    anchor_state: jnp.ndarray,
    config: LossConfig = LossConfig(),
) -> jnp.ndarray:
    """Focal loss from integer labels — no dense one-hot target tensor.

    Mathematically identical to :func:`focal_loss` with
    ``cls_targets = one_hot(matched_labels) * (state == POSITIVE)``, but the
    one-hot is an implicit ``labels == iota(K)`` compare that XLA fuses into
    the elementwise focal computation.  At the flagship bucket this removes a
    (B, 201600, 80) f32 target tensor (~0.5 GB of HBM writes+reads per step)
    from the hot path — the train step consumes this form.

    Args:
      cls_logits: (..., A, K) raw logits.
      matched_labels: (..., A) int32 matched class ids (only read where
        positive).
      anchor_state: (..., A) in {-1 ignore, 0 negative, 1 positive}.
    """
    num_classes = cls_logits.shape[-1]
    targets = (
        (anchor_state == matching.POSITIVE)[..., None]
        & (
            matched_labels[..., None]
            == jnp.arange(num_classes, dtype=jnp.int32)
        )
    ).astype(jnp.float32)
    return focal_loss(cls_logits, targets, anchor_state, config)


def smooth_l1_loss(
    box_preds: jnp.ndarray,
    box_targets: jnp.ndarray,
    anchor_state: jnp.ndarray,
    config: LossConfig = LossConfig(),
) -> jnp.ndarray:
    """Scalar smooth-L1 regression loss over positive anchors.

    Args:
      box_preds: (..., A, 4) predicted deltas.
      box_targets: (..., A, 4) encoded target deltas.
      anchor_state: (..., A).
    """
    preds = box_preds.astype(jnp.float32)
    targets = box_targets.astype(jnp.float32)
    diff = jnp.abs(preds - targets)
    beta = config.smooth_l1_beta
    loss = jnp.where(diff < beta, 0.5 * diff * diff / beta, diff - 0.5 * beta)

    positive = (anchor_state == matching.POSITIVE).astype(jnp.float32)
    loss = loss * positive[..., None]
    # Per-image normalization, then batch mean (see focal_loss).
    per_image = jnp.sum(loss, axis=(-2, -1))
    num_pos = jnp.sum(positive, axis=-1)
    return jnp.mean(per_image / jnp.maximum(num_pos, 1.0))


def total_loss_compact(
    cls_logits: jnp.ndarray,
    box_preds: jnp.ndarray,
    matched_labels: jnp.ndarray,
    box_targets: jnp.ndarray,
    anchor_state: jnp.ndarray,
    config: LossConfig = LossConfig(),
) -> dict[str, jnp.ndarray]:
    """:func:`total_loss` on compact (integer-label) targets — the step path."""
    cls = focal_loss_compact(cls_logits, matched_labels, anchor_state, config)
    box = smooth_l1_loss(box_preds, box_targets, anchor_state, config)
    return {
        "loss": cls + config.box_loss_weight * box,
        "cls_loss": cls,
        "box_loss": box,
    }


def total_loss(
    cls_logits: jnp.ndarray,
    box_preds: jnp.ndarray,
    cls_targets: jnp.ndarray,
    box_targets: jnp.ndarray,
    anchor_state: jnp.ndarray,
    config: LossConfig = LossConfig(),
) -> dict[str, jnp.ndarray]:
    cls = focal_loss(cls_logits, cls_targets, anchor_state, config)
    box = smooth_l1_loss(box_preds, box_targets, anchor_state, config)
    return {
        "loss": cls + config.box_loss_weight * box,
        "cls_loss": cls,
        "box_loss": box,
    }
