"""Cloud TPU pod-slice provisioning + job submission (the Batch AI layer).

Reference W3/W4 (SURVEY.md §2.1): a Makefile + `az` CLI calls create a Batch
AI cluster of N GPU VMs with blob storage mounted, and a job JSON submits
`mpirun python train.py` over those nodes.  TPU-native equivalent: `gcloud`
provisions a TPU pod slice (one LOGICAL resource — no per-VM fleet to
assemble), and job submission is `ssh --worker=all` running the SAME
`train.py --distributed-auto` on every host; `jax.distributed.initialize()`
does rank discovery from TPU metadata, so there is no mpirun, no hostfile,
and no container registry in the loop.

This module GENERATES the commands (dataclass config -> argv lists) and can
execute them when gcloud is present.  Generation is pure and unit-tested
(tests/unit/test_cluster.py); `--dry-run` prints exactly what would run —
the air-gapped analogue of checking the reference's cluster/job JSON into
the repo.

Usage:
    python -m batchai_retinanet_horovod_coco_tpu.launch.cluster \
        create --name ret-pod --accelerator v5litepod-256 --dry-run
    python -m ....launch.cluster submit --name ret-pod \
        -- --preset pod coco /mnt/coco --dry-run
    python -m ....launch.cluster status|delete --name ret-pod --dry-run
"""

from __future__ import annotations

import dataclasses
import shlex
import subprocess
import sys


@dataclasses.dataclass(frozen=True)
class TPUClusterConfig:
    """A TPU pod slice (the W3 'cluster' — one gcloud resource).

    ``accelerator``: e.g. v5litepod-8 .. v5litepod-256 (BASELINE.json's
    8->256-chip scaling range).  ``queued``: use queued-resources (the
    capacity-friendly path) instead of direct tpu-vm create.
    """

    name: str = "retinanet-pod"
    zone: str = "us-east5-b"
    project: str | None = None
    accelerator: str = "v5litepod-256"
    runtime_version: str = "v2-alpha-tpuv5-lite"
    spot: bool = False
    queued: bool = False
    network: str | None = None


def _base(cfg: TPUClusterConfig, *parts: str) -> list[str]:
    cmd = ["gcloud", *parts, f"--zone={cfg.zone}"]
    if cfg.project:
        cmd.append(f"--project={cfg.project}")
    return cmd


def create_command(cfg: TPUClusterConfig) -> list[str]:
    """Provision the slice (reference: `az batchai cluster create` + JSON)."""
    if cfg.queued:
        cmd = _base(
            cfg, "compute", "tpus", "queued-resources", "create", cfg.name
        )
        cmd += [
            f"--node-id={cfg.name}-0",
            f"--accelerator-type={cfg.accelerator}",
            f"--runtime-version={cfg.runtime_version}",
        ]
    else:
        cmd = _base(cfg, "compute", "tpus", "tpu-vm", "create", cfg.name)
        cmd += [
            f"--accelerator-type={cfg.accelerator}",
            f"--version={cfg.runtime_version}",
        ]
    if cfg.spot:
        cmd.append("--spot")
    if cfg.network:
        cmd.append(f"--network={cfg.network}")
    return cmd


def delete_command(cfg: TPUClusterConfig) -> list[str]:
    kind = "queued-resources" if cfg.queued else "tpu-vm"
    return _base(cfg, "compute", "tpus", kind, "delete", cfg.name, "--quiet")


def status_command(cfg: TPUClusterConfig) -> list[str]:
    kind = "queued-resources" if cfg.queued else "tpu-vm"
    return _base(cfg, "compute", "tpus", kind, "describe", cfg.name)


def submit_command(
    cfg: TPUClusterConfig,
    train_args: list[str],
    workdir: str = "batchai_retinanet_horovod_coco_tpu",
) -> list[str]:
    """The W4 'job': run train.py on EVERY host of the slice simultaneously.

    The reference needed an MPI job spec (processCount, hostfile, container
    image); here every host runs the identical command and the TPU metadata
    server supplies topology to ``jax.distributed.initialize()``
    (launch/pod.py) — `--distributed-auto` is the entire integration.

    ``workdir`` is resolved on the remote host (ssh lands in $HOME, so a
    relative path means "under the home dir").
    """
    train = " ".join(
        shlex.quote(a)
        for a in ["python", "train.py", *train_args, "--distributed-auto",
                  "--num-devices", "0"]
    )
    # Queued provisioning creates the node as '{name}-0' (create_command's
    # --node-id); direct tpu-vm create uses the name itself.
    node = f"{cfg.name}-0" if cfg.queued else cfg.name
    cmd = _base(cfg, "compute", "tpus", "tpu-vm", "ssh", node)
    cmd += [
        "--worker=all",
        f"--command=cd {shlex.quote(workdir)} && {train}",
    ]
    return cmd


def main(argv: list[str] | None = None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="cluster", description=__doc__.split("\n", 1)[0]
    )
    p.add_argument("action", choices=["create", "submit", "status", "delete"])
    p.add_argument("--name", default="retinanet-pod")
    p.add_argument("--zone", default="us-east5-b")
    p.add_argument("--project", default=None)
    p.add_argument("--accelerator", default="v5litepod-256")
    p.add_argument("--runtime-version", default="v2-alpha-tpuv5-lite")
    p.add_argument("--spot", action="store_true")
    p.add_argument("--queued", action="store_true",
                   help="provision via queued-resources")
    p.add_argument("--workdir", default="batchai_retinanet_horovod_coco_tpu",
                   help="remote dir (relative = under $HOME on each host)")
    p.add_argument("--dry-run", action="store_true",
                   help="print the gcloud command instead of running it")
    # Everything after `--` is the train.py command line (submit only);
    # flags BEFORE it are parsed strictly so typos error instead of being
    # silently dropped.
    argv = sys.argv[1:] if argv is None else list(argv)
    train_args: list[str] = []
    if "--" in argv:
        split = argv.index("--")
        argv, train_args = argv[:split], argv[split + 1:]
    args = p.parse_args(argv)
    if train_args and args.action != "submit":
        p.error("train.py args after '--' are only valid with 'submit'")

    cfg = TPUClusterConfig(
        name=args.name, zone=args.zone, project=args.project,
        accelerator=args.accelerator, runtime_version=args.runtime_version,
        spot=args.spot, queued=args.queued,
    )
    cmd = {
        "create": lambda: create_command(cfg),
        "delete": lambda: delete_command(cfg),
        "status": lambda: status_command(cfg),
        "submit": lambda: submit_command(cfg, train_args, args.workdir),
    }[args.action]()

    if args.dry_run:
        print(" ".join(shlex.quote(c) for c in cmd))
        return 0
    return subprocess.call(cmd)


if __name__ == "__main__":
    sys.exit(main())
