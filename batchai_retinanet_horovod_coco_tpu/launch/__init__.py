"""Multi-host bring-up: the Cloud TPU pod-slice launcher layer.

Replaces the reference's L5/H4 stack (SURVEY.md §2.1 W3/W4, §2.3 H4): Azure
Batch AI cluster provisioning + ``mpirun`` process launch + MPI rank
discovery.  On TPU pods the same ``train.py`` binary runs on every host and
``jax.distributed.initialize()`` replaces the MPI world bootstrap.
"""

from batchai_retinanet_horovod_coco_tpu.launch.pod import (
    DistributedConfig,
    initialize_distributed,
    shard_info,
)

_CLUSTER_EXPORTS = (
    "TPUClusterConfig",
    "create_command",
    "delete_command",
    "status_command",
    "submit_command",
)


def __getattr__(name: str):
    # Lazy (PEP 562): `python -m ...launch.cluster` would otherwise warn
    # about the module pre-existing in sys.modules (runpy double import).
    if name in _CLUSTER_EXPORTS:
        from batchai_retinanet_horovod_coco_tpu.launch import cluster

        return getattr(cluster, name)
    raise AttributeError(name)

__all__ = [
    "DistributedConfig",
    "TPUClusterConfig",
    "create_command",
    "delete_command",
    "initialize_distributed",
    "shard_info",
    "status_command",
    "submit_command",
]
