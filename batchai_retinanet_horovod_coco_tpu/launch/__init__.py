"""Multi-host bring-up: the Cloud TPU pod-slice launcher layer.

Replaces the reference's L5/H4 stack (SURVEY.md §2.1 W3/W4, §2.3 H4): Azure
Batch AI cluster provisioning + ``mpirun`` process launch + MPI rank
discovery.  On TPU pods the same ``train.py`` binary runs on every host and
``jax.distributed.initialize()`` replaces the MPI world bootstrap.
"""

from batchai_retinanet_horovod_coco_tpu.launch.pod import (
    DistributedConfig,
    initialize_distributed,
    shard_info,
)

__all__ = ["DistributedConfig", "initialize_distributed", "shard_info"]
