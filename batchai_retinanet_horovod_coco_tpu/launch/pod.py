"""TPU pod-slice process bring-up (the mpirun/MPI replacement).

Reference call stack 3.1 (SURVEY.md): Batch AI schedules N VMs, ``mpirun``
forks one process per GPU, ranks discover each other through MPI, and
``hvd.init()`` joins the world.  TPU-native equivalent: the SAME ``train.py``
is started once per host (by the pod launcher / `gcloud compute tpus ssh
--worker=all`), and ``jax.distributed.initialize()`` performs coordinator
discovery — on Cloud TPU VMs entirely from environment metadata, so the
zero-argument call is the whole bootstrap.  After it returns,
``jax.devices()`` is the GLOBAL device list and the mesh code
(parallel/mesh.py) works unchanged from 1 chip to a v5e-256 slice.

For CI / laptops the explicit (coordinator, num_processes, process_id) form
brings up a multi-process CPU "pod" (tests/distributed/test_pod_launch.py),
the analogue the reference never had (SURVEY.md §4: distributed testing —
none).
"""

from __future__ import annotations

import dataclasses

import jax


@dataclasses.dataclass(frozen=True)
class DistributedConfig:
    """How to join (or not join) a multi-process world.

    Default: single-process — ``initialize_distributed`` is a no-op, which is
    the 1-host dev path.  ``auto=True``: zero-argument
    ``jax.distributed.initialize()`` using Cloud TPU metadata.  Explicit
    coordinator fields: manual bring-up (CI, CPU multi-process).
    """

    auto: bool = False
    coordinator_address: str | None = None
    num_processes: int | None = None
    process_id: int | None = None
    local_device_ids: tuple[int, ...] | None = None


def initialize_distributed(config: DistributedConfig = DistributedConfig()) -> None:
    """Join the multi-process world per ``config``; idempotent for 1 process."""
    if config.auto:
        jax.distributed.initialize()
        return
    if config.coordinator_address is not None:
        jax.distributed.initialize(
            coordinator_address=config.coordinator_address,
            num_processes=config.num_processes,
            process_id=config.process_id,
            local_device_ids=config.local_device_ids,
        )
    # else: single-process run; nothing to do.


def shard_info() -> tuple[int, int]:
    """(shard_index, shard_count) for host data sharding = (process, #processes).

    The grain/tf.data idiom replacing Horovod's per-rank generator seeding
    (SURVEY.md M8): each host reads records[process_index::process_count].
    """
    return jax.process_index(), jax.process_count()
