"""RetinaNet assembly: backbone → FPN → shared heads → concatenated outputs.

Parity target: keras-retinanet's ``retinanet()`` graph builder (SURVEY.md M1).
The training model outputs, per image, dense per-anchor classification logits
(A, K) and box deltas (A, 4), concatenated over pyramid levels P3→P7 in the
SAME anchor order as ``ops.anchors.anchors_for_image_shape``: level-major,
then row-major over (y, x), then the 9 anchors of a location.  This ordering
contract is what lets targets/anchors be plain constants alongside the model
outputs; it is locked in by tests (tests/unit/test_model.py).

Unlike the reference there is no separate "bbox model" conversion step
(SURVEY.md M3): inference is just another jitted function over the same
params (evaluate/detect.py) since decode+NMS are ordinary device ops here.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from batchai_retinanet_horovod_coco_tpu.models.fpn import FPN
from batchai_retinanet_horovod_coco_tpu.models.heads import BoxHead, ClassificationHead
from batchai_retinanet_horovod_coco_tpu.models.resnet import ResNet
from batchai_retinanet_horovod_coco_tpu.ops.anchors import AnchorConfig


@dataclasses.dataclass(frozen=True)
class RetinaNetConfig:
    num_classes: int = 80
    backbone: str = "resnet50"
    norm_kind: str = "gn"  # "gn" | "bn" | "frozen_bn"  (see models/resnet.py)
    # Stem formulation (models/resnet.py StemConv): space_to_depth is the
    # MLPerf-equivalent reformulation of the 7x7/2 conv — identical math,
    # measured 3.7% faster end-to-end on v5e (the plain 3-channel stem runs
    # the MXU at ~4% occupancy).  "conv" restores the canonical form.
    stem: str = "space_to_depth"
    # Width-packed stage2 (models/resnet.py): the C=64 stage runs with W
    # pairs folded into channels so its convs fill the 128-lane MXU —
    # math-identical, same param tree.  MEASURED NEGATIVE at the flagship
    # bucket on v5e (58.3 vs 60.7 imgs/s at b8: stage2 is mostly
    # bandwidth-bound there, so the packed kernels' 2x MACs cost more than
    # the lane-occupancy win; PARITY.md round 3).  Kept as an exact,
    # tested reformulation for narrow-channel-bound shapes/hardware.
    # ResNet backbones only; needs W_img divisible by 8.
    pack_width: bool = False
    # "avg" swaps the ResNet stem maxpool for a tie-free avg pool — a
    # diagnostic config for gradient-parity tests under GSPMD spatial
    # partitioning (models/resnet.py ResNet.stem_pool); requires
    # stem="conv".  ResNet backbones only.
    stem_pool: str = "max"
    fpn_channels: int = 256
    head_width: int = 256
    head_depth: int = 4
    prior_prob: float = 0.01
    anchor: AnchorConfig = AnchorConfig()
    dtype: Any = jnp.bfloat16

    @property
    def anchors_per_location(self) -> int:
        return self.anchor.num_anchors_per_location


_BACKBONE_STAGES = {
    "resnet18": None,  # not a bottleneck net; unsupported, kept for error msg
    "resnet50": (3, 4, 6, 3),
    "resnet101": (3, 4, 23, 3),
    "resnet152": (3, 8, 36, 3),
    # One block per stage: for fast CI on the virtual CPU mesh only.
    "resnet_test": (1, 1, 1, 1),
}

BACKBONES = tuple(
    k for k, v in _BACKBONE_STAGES.items() if v is not None
) + (
    "mobilenet", "mobilenet050", "vgg16", "vgg19",
    "densenet121", "densenet169", "densenet201",
)


def build_backbone(cfg: "RetinaNetConfig"):
    """Backbone registry: every entry returns a module producing
    {"c3", "c4", "c5"} at strides 8/16/32 (the FPN input contract).

    The reference library's backbone families (SURVEY.md M2: ResNet primary;
    mobilenet/vgg siblings in keras_retinanet/models/).  ``norm_kind`` and
    ``stem`` apply where the architecture has them (VGG has no norm layers;
    only ResNet has the 7x7/2 stem the space_to_depth mode reformulates).
    """
    name = cfg.backbone
    stages = _BACKBONE_STAGES.get(name)
    if cfg.pack_width and stages is None:
        raise ValueError(
            f"pack_width is a ResNet-stage2 reformulation; backbone "
            f"{name!r} does not support it"
        )
    if cfg.stem_pool != "max" and stages is None:
        # Mirror the pack_width guard above: a diagnostic knob that only
        # the ResNet stem implements must not be silently ignored.
        raise ValueError(
            f"stem_pool={cfg.stem_pool!r} is only supported by ResNet "
            f"backbones, not {name!r}"
        )
    if stages is not None:
        return ResNet(
            stage_sizes=stages,
            norm_kind=cfg.norm_kind,
            dtype=cfg.dtype,
            stem=cfg.stem,
            pack_width=cfg.pack_width,
            stem_pool=cfg.stem_pool,
            name="backbone",
        )
    if name in ("mobilenet", "mobilenet050"):
        from batchai_retinanet_horovod_coco_tpu.models.mobilenet import (
            MobileNetV1,
        )

        return MobileNetV1(
            alpha=0.5 if name == "mobilenet050" else 1.0,
            norm_kind=cfg.norm_kind,
            dtype=cfg.dtype,
            name="backbone",
        )
    if name in ("vgg16", "vgg19"):
        from batchai_retinanet_horovod_coco_tpu.models.vgg import VGG

        return VGG(
            stage_sizes=(2, 2, 3, 3, 3) if name == "vgg16" else (2, 2, 4, 4, 4),
            dtype=cfg.dtype,
            name="backbone",
        )
    if name in ("densenet121", "densenet169", "densenet201"):
        from batchai_retinanet_horovod_coco_tpu.models.densenet import (
            DENSENET_STAGES,
            DenseNet,
        )

        return DenseNet(
            stage_sizes=DENSENET_STAGES[name],
            norm_kind=cfg.norm_kind,
            dtype=cfg.dtype,
            name="backbone",
        )
    raise ValueError(f"unsupported backbone: {name!r}")


class RetinaNet(nn.Module):
    config: RetinaNetConfig

    @nn.compact
    def __call__(
        self,
        images: jnp.ndarray,
        train: bool = False,
        return_levels: bool | str = False,
    ) -> dict[str, Any]:
        """(B, H, W, 3) float images → {"cls_logits": (B, A, K), "box_deltas": (B, A, 4)}.

        ``return_levels=True`` returns the PER-LEVEL anchor-major outputs
        instead ({"cls_levels": tuple of (B, A_l, K), "box_levels": ...},
        P3→P7 in anchor order) and skips the concatenation.
        ``return_levels="nhwc"`` returns the RAW conv outputs per level
        ((B, h_l, w_l, A·K) / (B, h_l, w_l, A·4)) — no anchor-major retile,
        no concat; the train step consumes this via
        ``losses.total_loss_compact_nhwc`` (the retile+concat+split complex
        measured ~4 ms of the b8 flagship step, round-3 profile).
        """
        cfg = self.config
        # named_scope: phase labels in profiler traces (SURVEY.md §5.1).
        with jax.named_scope("backbone"):
            features = build_backbone(cfg)(images, train=train)
        with jax.named_scope("fpn"):
            pyramid = FPN(
                channels=cfg.fpn_channels, dtype=cfg.dtype, name="fpn"
            )(features)

        cls_head = ClassificationHead(
            num_classes=cfg.num_classes,
            anchors_per_location=cfg.anchors_per_location,
            width=cfg.head_width,
            depth=cfg.head_depth,
            prior_prob=cfg.prior_prob,
            dtype=cfg.dtype,
            name="cls_head",
        )
        box_head = BoxHead(
            anchors_per_location=cfg.anchors_per_location,
            width=cfg.head_width,
            depth=cfg.head_depth,
            dtype=cfg.dtype,
            name="box_head",
        )

        flatten = return_levels != "nhwc"
        cls_out, box_out = [], []
        with jax.named_scope("heads"):
            for level in cfg.anchor.levels:  # P3 → P7, matching anchor order
                feat = pyramid[f"p{level}"]
                cls_out.append(cls_head(feat, flatten=flatten))
                box_out.append(box_head(feat, flatten=flatten))

        if return_levels == "nhwc":
            # Raw dtype (bf16): an f32 cast here would double the final
            # head convs' output writes (~516 MB/step at the flagship
            # bucket); the nhwc loss casts f32 inside its elementwise
            # fusion instead.
            return {"cls_levels": tuple(cls_out), "box_levels": tuple(box_out)}
        if return_levels:
            # Losses run in f32; cast per level (fuses into the head convs).
            return {
                "cls_levels": tuple(o.astype(jnp.float32) for o in cls_out),
                "box_levels": tuple(o.astype(jnp.float32) for o in box_out),
            }
        return {
            # Losses run in f32; cast once here so downstream ops are f32.
            "cls_logits": jnp.concatenate(cls_out, axis=1).astype(jnp.float32),
            "box_deltas": jnp.concatenate(box_out, axis=1).astype(jnp.float32),
        }


def build_retinanet(config: RetinaNetConfig | None = None) -> RetinaNet:
    return RetinaNet(config=config or RetinaNetConfig())
