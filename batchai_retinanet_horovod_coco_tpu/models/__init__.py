"""Flax model zoo: ResNet backbones, FPN, RetinaNet heads.

Capability parity with the reference's model layer (SURVEY.md M1-M3:
keras-retinanet ``models/resnet.py`` + ``models/retinanet.py``), redesigned
for TPU: NHWC layouts, bfloat16 compute with float32 params, GroupNorm or
(frozen) BatchNorm, everything traced once under jit with static shapes.
"""

from batchai_retinanet_horovod_coco_tpu.models.fpn import FPN
from batchai_retinanet_horovod_coco_tpu.models.heads import BoxHead, ClassificationHead
from batchai_retinanet_horovod_coco_tpu.models.resnet import ResNet, resnet50
from batchai_retinanet_horovod_coco_tpu.models.retinanet import (
    RetinaNet,
    RetinaNetConfig,
    build_retinanet,
)

__all__ = [
    "FPN",
    "BoxHead",
    "ClassificationHead",
    "ResNet",
    "RetinaNet",
    "RetinaNetConfig",
    "build_retinanet",
    "resnet50",
]
