"""ImageNet-pretrained backbone import (torch ResNet-50 → flax params).

Parity target: the reference initialized its backbone from ImageNet weights
(SURVEY.md M2/call stack 3.2 "load ImageNet weights") and fine-tuned with
frozen BN.  This environment is air-gapped with no checkpoint on disk
(SURVEY.md §7.3 hard part 5 — the #1 external dependency for mAP 36.0), so
the from-scratch GroupNorm recipe is the default; this module closes the
capability gap for when weights ARE available: it maps a torchvision-style
``resnet50`` state dict (``.pth`` via torch, ``.npz``, or a plain array
dict) onto ``models/resnet.py``'s parameter tree.

Layout notes: torch convs are OIHW → flax HWIO; torch BN
weight/bias/running_mean/running_var → flax scale/bias + batch_stats
mean/var.  Use ``norm_kind="frozen_bn"`` (the reference recipe) or ``"bn"``
— GroupNorm models have no BN stats to receive.  torchvision's resnet50 is
v1.5 (stride on the 3x3), matching models/resnet.py exactly; only SAME-vs-
explicit padding differs at borders, which fine-tuning absorbs.
"""

from __future__ import annotations

from typing import Any, Mapping

import numpy as np

# (torch stem/stage prefixes) → (flax module names)
_STAGE_OF_LAYER = {f"layer{i}": f"stage{i + 1}" for i in range(1, 5)}


def _conv(w: np.ndarray) -> np.ndarray:
    """OIHW → HWIO."""
    return np.ascontiguousarray(np.transpose(w, (2, 3, 1, 0)))


def load_state_dict(path: str) -> dict[str, np.ndarray]:
    """Read a state dict from .pth (torch) or .npz into numpy arrays."""
    if path.endswith(".npz"):
        with np.load(path) as z:
            return {k: z[k] for k in z.files}
    import torch

    try:
        sd = torch.load(path, map_location="cpu", weights_only=True)
    except Exception as e:
        raise ValueError(
            f"{path} is not a plain tensor state dict (full pickled modules "
            "are not supported; save model.state_dict() instead)"
        ) from e
    return {k: v.numpy() for k, v in sd.items()}


def convert_torch_resnet50(
    state_dict: Mapping[str, np.ndarray],
) -> tuple[dict, dict]:
    """torchvision resnet50 state dict → (params, batch_stats) subtrees.

    Returns the ``backbone`` subtrees for models/resnet.py with
    ``norm_kind="frozen_bn"``/``"bn"``.  The classifier head (``fc.*``) is
    dropped — detection uses C3..C5 only.
    """
    params: dict[str, Any] = {}
    batch_stats: dict[str, Any] = {}

    def put(tree: dict, path: list[str], leaf: np.ndarray) -> None:
        node = tree
        for p in path[:-1]:
            node = node.setdefault(p, {})
        node[path[-1]] = np.asarray(leaf)

    def import_bn(flax_name: list[str], torch_prefix: str) -> None:
        put(params, flax_name + ["scale"], state_dict[f"{torch_prefix}.weight"])
        put(params, flax_name + ["bias"], state_dict[f"{torch_prefix}.bias"])
        put(
            batch_stats,
            flax_name + ["mean"],
            state_dict[f"{torch_prefix}.running_mean"],
        )
        put(
            batch_stats,
            flax_name + ["var"],
            state_dict[f"{torch_prefix}.running_var"],
        )

    put(params, ["stem_conv", "kernel"], _conv(state_dict["conv1.weight"]))
    import_bn(["stem_norm"], "bn1")

    for layer, stage in _STAGE_OF_LAYER.items():
        block = 0
        while f"{layer}.{block}.conv1.weight" in state_dict:
            fb = f"{stage}_block{block}"
            tb = f"{layer}.{block}"
            for k in (1, 2, 3):
                put(
                    params,
                    [fb, f"conv{k}", "kernel"],
                    _conv(state_dict[f"{tb}.conv{k}.weight"]),
                )
                import_bn([fb, f"norm{k}"], f"{tb}.bn{k}")
            if f"{tb}.downsample.0.weight" in state_dict:
                put(
                    params,
                    [fb, "proj", "kernel"],
                    _conv(state_dict[f"{tb}.downsample.0.weight"]),
                )
                import_bn([fb, "proj_norm"], f"{tb}.downsample.1")
            block += 1
        if block == 0:
            raise ValueError(f"state dict has no blocks for {layer}")

    return params, batch_stats


def _merge(dst: dict, src: Mapping, path: str) -> None:
    for k, v in src.items():
        if k not in dst:
            raise ValueError(f"unknown param {path}/{k} in imported weights")
        if isinstance(v, Mapping):
            _merge(dst[k], v, f"{path}/{k}")
        else:
            if tuple(dst[k].shape) != tuple(np.shape(v)):
                raise ValueError(
                    f"shape mismatch at {path}/{k}: model {dst[k].shape} "
                    f"vs imported {np.shape(v)}"
                )
            dst[k] = np.asarray(v, dtype=np.asarray(dst[k]).dtype)


def _uncovered(dst: Mapping, src: Mapping, path: str) -> list[str]:
    """Leaves of ``dst`` that ``src`` does not provide (src keys ⊆ dst keys)."""
    missing: list[str] = []
    for k, v in dst.items():
        if k not in src:
            missing.append(f"{path}/{k}")
        elif isinstance(v, Mapping):
            missing.extend(_uncovered(v, src[k], f"{path}/{k}"))
    return missing


def apply_backbone_weights(
    params: dict,
    batch_stats: dict,
    imported_params: dict,
    imported_stats: dict,
) -> tuple[dict, dict]:
    """Merge imported backbone subtrees into full model trees (returns copies).

    ``params``/``batch_stats`` are the model's initialized variable trees
    (must contain a ``backbone`` entry; frozen_bn/bn models also in
    batch_stats).  Shape mismatches raise, and so does PARTIAL coverage of
    the backbone (e.g. a resnet50 dict into a resnet101 model, whose extra
    stage4 blocks would otherwise stay silently random) — silently dropping
    or skipping tensors is how pretrained imports rot.
    """
    import jax

    new_params = jax.tree.map(np.asarray, params)
    new_stats = jax.tree.map(np.asarray, batch_stats)
    if "backbone" not in new_params:
        raise ValueError("model params have no 'backbone' subtree")
    _merge(new_params["backbone"], imported_params, "backbone")
    missing = _uncovered(new_params["backbone"], imported_params, "backbone")
    if imported_stats:
        if "backbone" not in new_stats:
            raise ValueError(
                "imported weights carry BN stats but the model has none "
                "(use norm_kind='frozen_bn' or 'bn')"
            )
        _merge(new_stats["backbone"], imported_stats, "backbone")
        missing += _uncovered(new_stats["backbone"], imported_stats, "backbone")
    if missing:
        head = ", ".join(missing[:5])
        raise ValueError(
            f"imported weights leave {len(missing)} backbone leaves "
            f"uninitialized (model deeper than the checkpoint?): {head}"
            + ("..." if len(missing) > 5 else "")
        )
    return new_params, new_stats
