"""DenseNet-BC backbone exposing C3, C4, C5 (strides 8/16/32).

Parity target: keras-retinanet's densenet backbone family
(``keras_retinanet/models/densenet.py`` — densenet121/169/201 as RetinaNet
backbones, the last of the reference's era backbone families, SURVEY.md M2).
Rebuilt in flax: BC variant (1x1 bottleneck to 4·growth before every 3x3,
transitions with 0.5 compression), growth rate 32.

Feature taps: each dense block's concatenated output BEFORE the transition
that downsamples for the next block — block2 @ stride 8 (c3), block3 @
stride 16 (c4), block4 + final norm @ stride 32 (c5).  Documented
divergence: the C3/C4 taps here come AFTER a shared block-out norm+relu
(the transition's norm is hoisted before the tap so both consumers share
it), whereas keras-applications taps the raw ``convN_blockM_concat``
output and normalizes inside the transition.  Equivalent for from-scratch
training (one extra norm+relu on the FPN lateral input); if a pretrained
DenseNet import path is ever added, the tap must move before the
``blockN_out_norm`` to match upstream activations exactly.

TPU note: dense connectivity concatenates along channels, so the 3x3 convs
contract over ever-wider inputs (MXU-friendly) but every block re-reads the
whole growing feature map — bandwidth-heavier per FLOP than ResNet.  NHWC,
bf16 activations / f32 params, same norm factory as ResNet.
"""

from __future__ import annotations

from collections.abc import Sequence

import flax.linen as nn
import jax.numpy as jnp

from batchai_retinanet_horovod_coco_tpu.models.resnet import NormFactory

# block sizes per variant (growth 32, init 64, compression 0.5)
DENSENET_STAGES = {
    "densenet121": (6, 12, 24, 16),
    "densenet169": (6, 12, 32, 32),
    "densenet201": (6, 12, 48, 32),
}


class _DenseLayer(nn.Module):
    """norm → relu → 1x1 (4·growth) → norm → relu → 3x3 (growth); concat."""

    growth: int
    norm: NormFactory
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        y = self.norm("norm1", train)(x)
        y = nn.relu(y)
        y = nn.Conv(
            4 * self.growth, (1, 1), use_bias=False,
            dtype=self.dtype, param_dtype=jnp.float32, name="conv1",
        )(y)
        y = self.norm("norm2", train)(y)
        y = nn.relu(y)
        y = nn.Conv(
            self.growth, (3, 3), padding="SAME", use_bias=False,
            dtype=self.dtype, param_dtype=jnp.float32, name="conv2",
        )(y)
        return jnp.concatenate([x, y], axis=-1)


class DenseNet(nn.Module):
    """DenseNet-BC; ``stage_sizes`` = layers per dense block (4 blocks)."""

    stage_sizes: Sequence[int]
    growth: int = 32
    init_features: int = 64
    norm_kind: str = "gn"
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(
        self, x: jnp.ndarray, train: bool = False
    ) -> dict[str, jnp.ndarray]:
        norm = NormFactory(self.norm_kind, self.dtype)
        x = x.astype(self.dtype)
        x = nn.Conv(
            self.init_features, (7, 7), strides=(2, 2), padding="SAME",
            use_bias=False, dtype=self.dtype, param_dtype=jnp.float32,
            name="stem_conv",
        )(x)
        x = norm("stem_norm", train)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")  # stride 4

        features: dict[str, jnp.ndarray] = {}
        for block, num_layers in enumerate(self.stage_sizes):
            for layer in range(num_layers):
                x = _DenseLayer(
                    growth=self.growth, norm=norm, dtype=self.dtype,
                    name=f"block{block + 1}_layer{layer}",
                )(x, train=train)
            # Shared norm+relu: tail of the block for the c-tap, head of the
            # transition (or the final norm for the last block).
            x = norm(f"block{block + 1}_out_norm", train)(x)
            x = nn.relu(x)
            # Blocks 2/3/4 run at strides 8/16/32 → c3/c4/c5.
            if block >= 1:
                features[f"c{block + 2}"] = x
            if block < len(self.stage_sizes) - 1:
                x = nn.Conv(
                    x.shape[-1] // 2, (1, 1), use_bias=False,
                    dtype=self.dtype, param_dtype=jnp.float32,
                    name=f"transition{block + 1}_conv",
                )(x)
                x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        return features
