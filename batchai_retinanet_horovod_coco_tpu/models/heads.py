"""Shared classification and box-regression subnets.

Parity target: keras-retinanet's ``default_classification_model`` /
``default_regression_model`` (SURVEY.md M1): depth-4 conv-256 subnets shared
across pyramid levels; the classification head's final bias is initialized to
-log((1-pi)/pi) with pi=0.01 so training starts with ~1% foreground
probability (the RetinaNet prior trick), and outputs are raw logits (the loss
is computed on logits; apply sigmoid only at inference).

The heads are flax modules applied to each level with the SAME parameters
(weight sharing falls out of calling one module instance on every level
inside RetinaNet).
"""

from __future__ import annotations

import math

import flax.linen as nn
import jax.numpy as jnp


def _head_conv(features: int, name: str, dtype, bias_init=nn.initializers.zeros):
    return nn.Conv(
        features,
        (3, 3),
        padding="SAME",
        dtype=dtype,
        param_dtype=jnp.float32,
        kernel_init=nn.initializers.normal(stddev=0.01),
        bias_init=bias_init,
        name=name,
    )


class ClassificationHead(nn.Module):
    num_classes: int
    anchors_per_location: int = 9
    width: int = 256
    depth: int = 4
    prior_prob: float = 0.01
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray, flatten: bool = True) -> jnp.ndarray:
        """(B, H, W, C) → (B, H*W*anchors, num_classes) logits.

        ``flatten=False`` returns the raw (B, H, W, anchors*num_classes)
        conv output: the anchor-major flatten retiles the lane dimension
        (720 → K-minor), a real layout copy per level; the NHWC-direct loss
        path (losses.total_loss_compact_nhwc) skips it.
        """
        for i in range(self.depth):
            x = _head_conv(self.width, f"conv{i}", self.dtype)(x)
            x = nn.relu(x)
        bias = -math.log((1.0 - self.prior_prob) / self.prior_prob)
        x = _head_conv(
            self.num_classes * self.anchors_per_location,
            "logits",
            self.dtype,
            bias_init=nn.initializers.constant(bias),
        )(x)
        if not flatten:
            return x
        b, h, w, _ = x.shape
        return x.reshape(b, h * w * self.anchors_per_location, self.num_classes)


class BoxHead(nn.Module):
    anchors_per_location: int = 9
    width: int = 256
    depth: int = 4
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray, flatten: bool = True) -> jnp.ndarray:
        """(B, H, W, C) → (B, H*W*anchors, 4) deltas (see ClassificationHead
        for ``flatten=False``)."""
        for i in range(self.depth):
            x = _head_conv(self.width, f"conv{i}", self.dtype)(x)
            x = nn.relu(x)
        x = _head_conv(4 * self.anchors_per_location, "deltas", self.dtype)(x)
        if not flatten:
            return x
        b, h, w, _ = x.shape
        return x.reshape(b, h * w * self.anchors_per_location, 4)
