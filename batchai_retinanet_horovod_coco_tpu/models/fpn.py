"""Feature Pyramid Network producing P3..P7 from C3..C5.

Parity target: keras-retinanet's ``__create_pyramid_features`` (SURVEY.md M1):
lateral 1x1 convs, nearest-neighbor top-down pathway, 3x3 output convs, plus
P6 = stride-2 conv on C5 and P7 = relu + stride-2 conv on P6.

Upsampling resizes to the exact lateral shape (jax.image.resize, nearest),
which keeps odd/ceil dimensions consistent with SAME-padded stride arithmetic
— XLA lowers this to a cheap gather with static shapes.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp


class FPN(nn.Module):
    """C3..C5 → P3..P7, all with ``channels`` features."""

    channels: int = 256
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, features: dict[str, jnp.ndarray]) -> dict[str, jnp.ndarray]:
        conv = lambda f, k, s, name: nn.Conv(  # noqa: E731
            f,
            (k, k),
            strides=(s, s),
            padding="SAME",
            dtype=self.dtype,
            param_dtype=jnp.float32,
            name=name,
        )
        c3, c4, c5 = features["c3"], features["c4"], features["c5"]

        m5 = conv(self.channels, 1, 1, "lateral_c5")(c5)
        m4 = conv(self.channels, 1, 1, "lateral_c4")(c4)
        m3 = conv(self.channels, 1, 1, "lateral_c3")(c3)

        m4 = m4 + _upsample_to(m5, m4.shape)
        m3 = m3 + _upsample_to(m4, m3.shape)

        p3 = conv(self.channels, 3, 1, "out_p3")(m3)
        p4 = conv(self.channels, 3, 1, "out_p4")(m4)
        p5 = conv(self.channels, 3, 1, "out_p5")(m5)
        p6 = conv(self.channels, 3, 2, "out_p6")(c5)
        p7 = conv(self.channels, 3, 2, "out_p7")(nn.relu(p6))
        return {"p3": p3, "p4": p4, "p5": p5, "p6": p6, "p7": p7}


def _upsample_to(x: jnp.ndarray, target_shape: tuple[int, ...]) -> jnp.ndarray:
    """Nearest-neighbor upsample NHWC ``x`` to the target H, W."""
    b, _, _, c = x.shape
    th, tw = target_shape[1], target_shape[2]
    return jax.image.resize(x, (b, th, tw, c), method="nearest")
