"""VGG-16/19 backbone exposing C3, C4, C5 (strides 8/16/32).

Parity target: keras-retinanet's vgg backbone
(``keras_retinanet/models/vgg.py`` — uses block3_pool/block4_pool/
block5_pool as the FPN inputs, SURVEY.md M2's sibling models).  Classic VGG
has no normalization layers; the flax rebuild keeps that (``norm_kind`` is
accepted for interface uniformity and ignored), so ``--f32`` or bf16 both
work without mutable state.
"""

from __future__ import annotations

from collections.abc import Sequence

import flax.linen as nn
import jax.numpy as jnp


class VGG(nn.Module):
    """VGG body; returns {"c3", "c4", "c5"} = pooled blocks 3/4/5."""

    stage_sizes: Sequence[int]  # convs per block, e.g. (2, 2, 3, 3, 3)
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> dict[str, jnp.ndarray]:
        del train  # no norm/dropout state in the detection backbone
        widths = (64, 128, 256, 512, 512)
        x = x.astype(self.dtype)
        features: dict[str, jnp.ndarray] = {}
        for block, (n_convs, width) in enumerate(
            zip(self.stage_sizes, widths), 1
        ):
            for i in range(n_convs):
                x = nn.Conv(
                    width, (3, 3), padding="SAME",
                    dtype=self.dtype, param_dtype=jnp.float32,
                    name=f"block{block}_conv{i + 1}",
                )(x)
                x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
            if block >= 3:  # pool3 /8, pool4 /16, pool5 /32
                features[f"c{block}"] = x
        return features


def vgg16(dtype: jnp.dtype = jnp.bfloat16) -> VGG:
    return VGG(stage_sizes=(2, 2, 3, 3, 3), dtype=dtype)


def vgg19(dtype: jnp.dtype = jnp.bfloat16) -> VGG:
    return VGG(stage_sizes=(2, 2, 4, 4, 4), dtype=dtype)
