"""MobileNetV1 backbone exposing C3, C4, C5 (strides 8/16/32).

Parity target: keras-retinanet's mobilenet backbone family
(``keras_retinanet/models/mobilenet.py`` — the library supported
mobilenet128/160/192/224 at several width multipliers as RetinaNet
backbones, SURVEY.md M2's sibling models).  Rebuilt in flax with the same
13-block depthwise-separable topology; ``alpha`` is the width multiplier.

TPU note: depthwise convs don't use the MXU (one MAC per channel — they
lower to VPU ops), so MobileNet trades MXU-friendly FLOPs for bandwidth;
it is the small/edge option, not the fast-TPU option.  NHWC, bf16
activations / f32 params, same norm factory as ResNet.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from batchai_retinanet_horovod_coco_tpu.models.resnet import NormFactory


class _DepthwiseSeparable(nn.Module):
    """3x3 depthwise (+stride) → BN/GN → relu6 → 1x1 pointwise → norm → relu6."""

    filters: int
    stride: int
    norm: NormFactory
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        in_ch = x.shape[-1]
        x = nn.Conv(
            in_ch,
            (3, 3),
            strides=(self.stride, self.stride),
            padding="SAME",
            feature_group_count=in_ch,  # depthwise
            use_bias=False,
            dtype=self.dtype,
            param_dtype=jnp.float32,
            name="dw",
        )(x)
        x = self.norm("dw_norm", train)(x)
        x = nn.relu6(x)
        x = nn.Conv(
            self.filters,
            (1, 1),
            use_bias=False,
            dtype=self.dtype,
            param_dtype=jnp.float32,
            name="pw",
        )(x)
        x = self.norm("pw_norm", train)(x)
        return nn.relu6(x)


class MobileNetV1(nn.Module):
    """The 13-block MobileNetV1 body; returns {"c3", "c4", "c5"}."""

    alpha: float = 1.0
    norm_kind: str = "gn"
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> dict[str, jnp.ndarray]:
        norm = NormFactory(self.norm_kind, self.dtype)

        def width(ch: int) -> int:
            scaled = int(ch * self.alpha)
            # GroupNorm(32) needs channel counts divisible by 32.
            return max(32, (scaled // 32) * 32)

        x = x.astype(self.dtype)
        x = nn.Conv(
            width(32), (3, 3), strides=(2, 2), padding="SAME", use_bias=False,
            dtype=self.dtype, param_dtype=jnp.float32, name="stem",
        )(x)
        x = norm("stem_norm", train)(x)
        x = nn.relu6(x)

        # (filters, stride) for the 13 depthwise-separable blocks; C3/C4/C5
        # are the last outputs at strides 8/16/32.
        blocks = [
            (64, 1), (128, 2), (128, 1), (256, 2), (256, 1),
            (512, 2), (512, 1), (512, 1), (512, 1), (512, 1), (512, 1),
            (1024, 2), (1024, 1),
        ]
        features: dict[str, jnp.ndarray] = {}
        taps = {5: "c3", 11: "c4", 13: "c5"}  # 1-based block index
        for i, (filters, stride) in enumerate(blocks, 1):
            x = _DepthwiseSeparable(
                filters=width(filters), stride=stride, norm=norm,
                dtype=self.dtype, name=f"block{i}",
            )(x, train=train)
            if i in taps:
                features[taps[i]] = x
        return features
