"""ResNet backbone (v1.5 bottleneck) exposing C3, C4, C5 feature maps.

Parity target: keras-retinanet's ResNet-50 backbone (SURVEY.md M2,
``models/resnet.py`` + the keras-resnet dependency), which feeds C3..C5 into
the FPN and freezes BatchNorm during detection fine-tuning.

TPU-first design:
- NHWC layout (XLA:TPU's native conv layout), bfloat16 activations with
  float32 params by default — convs hit the MXU in bf16.
- Norm is pluggable:
  * ``"gn"`` (default): GroupNorm(32) — batch-size independent, no mutable
    state, the right choice for from-scratch training in an air-gapped env
    (SURVEY.md §7.3 hard part 5);
  * ``"bn"``: BatchNorm with running stats (mutable ``batch_stats``);
  * ``"frozen_bn"``: running-stats-only BatchNorm (never updates), matching
    the reference's frozen-BN fine-tuning recipe when pretrained weights are
    supplied.
- Strided 3x3 in the bottleneck's middle conv (v1.5), SAME padding so spatial
  dims follow ceil(H/stride) — consistent with ops.anchors.feature_shape.
"""

from __future__ import annotations

from collections.abc import Callable, Sequence
from typing import Any

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class NormFactory:
    """Builds the configured norm layer; see module docstring for options."""

    def __init__(self, kind: str, dtype: jnp.dtype):
        if kind not in ("gn", "bn", "frozen_bn"):
            raise ValueError(f"unknown norm kind: {kind!r}")
        self.kind = kind
        self.dtype = dtype

    def __call__(self, name: str, train: bool) -> Callable:
        if self.kind == "gn":
            return nn.GroupNorm(
                num_groups=32, dtype=self.dtype, name=name, param_dtype=jnp.float32
            )
        use_running = (self.kind == "frozen_bn") or (not train)
        return nn.BatchNorm(
            use_running_average=use_running,
            momentum=0.9,
            dtype=self.dtype,
            param_dtype=jnp.float32,
            name=name,
        )


class BottleneckBlock(nn.Module):
    """1x1 → 3x3(stride) → 1x1(x4) with projection shortcut on shape change."""

    filters: int
    stride: int
    norm: NormFactory
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        conv = lambda f, k, s, name: nn.Conv(  # noqa: E731
            f,
            (k, k),
            strides=(s, s),
            padding="SAME",
            use_bias=False,
            dtype=self.dtype,
            param_dtype=jnp.float32,
            name=name,
        )
        residual = x
        y = conv(self.filters, 1, 1, "conv1")(x)
        y = self.norm("norm1", train)(y)
        y = nn.relu(y)
        y = conv(self.filters, 3, self.stride, "conv2")(y)
        y = self.norm("norm2", train)(y)
        y = nn.relu(y)
        y = conv(self.filters * 4, 1, 1, "conv3")(y)
        y = self.norm("norm3", train)(y)
        if residual.shape != y.shape:
            residual = conv(self.filters * 4, 1, self.stride, "proj")(x)
            residual = self.norm("proj_norm", train)(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    """ResNet exposing {"c3", "c4", "c5"} (strides 8/16/32)."""

    stage_sizes: Sequence[int]
    norm_kind: str = "gn"
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> dict[str, jnp.ndarray]:
        norm = NormFactory(self.norm_kind, self.dtype)
        x = x.astype(self.dtype)
        x = nn.Conv(
            64,
            (7, 7),
            strides=(2, 2),
            padding="SAME",
            use_bias=False,
            dtype=self.dtype,
            param_dtype=jnp.float32,
            name="stem_conv",
        )(x)
        x = norm("stem_norm", train)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")

        features: dict[str, jnp.ndarray] = {}
        filters = 64
        for stage, num_blocks in enumerate(self.stage_sizes):
            stride = 1 if stage == 0 else 2
            for block in range(num_blocks):
                x = BottleneckBlock(
                    filters=filters,
                    stride=stride if block == 0 else 1,
                    norm=norm,
                    dtype=self.dtype,
                    name=f"stage{stage + 2}_block{block}",
                )(x, train=train)
            if stage >= 1:  # C3 at stride 8, C4 at 16, C5 at 32
                features[f"c{stage + 2}"] = x
            filters *= 2
        return features


def resnet50(norm_kind: str = "gn", dtype: jnp.dtype = jnp.bfloat16) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 6, 3), norm_kind=norm_kind, dtype=dtype)
