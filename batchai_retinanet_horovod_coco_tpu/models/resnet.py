"""ResNet backbone (v1.5 bottleneck) exposing C3, C4, C5 feature maps.

Parity target: keras-retinanet's ResNet-50 backbone (SURVEY.md M2,
``models/resnet.py`` + the keras-resnet dependency), which feeds C3..C5 into
the FPN and freezes BatchNorm during detection fine-tuning.

TPU-first design:
- NHWC layout (XLA:TPU's native conv layout), bfloat16 activations with
  float32 params by default — convs hit the MXU in bf16.
- Norm is pluggable:
  * ``"gn"`` (default): GroupNorm(32) — batch-size independent, no mutable
    state, the right choice for from-scratch training in an air-gapped env
    (SURVEY.md §7.3 hard part 5);
  * ``"bn"``: BatchNorm with running stats (mutable ``batch_stats``);
  * ``"frozen_bn"``: running-stats-only BatchNorm (never updates), matching
    the reference's frozen-BN fine-tuning recipe when pretrained weights are
    supplied.
- Strided 3x3 in the bottleneck's middle conv (v1.5), symmetric torch-style
  padding (k//2 each side) so imported torchvision weights see the exact
  sampling grid they were trained with; spatial dims still follow
  ceil(H/stride) — consistent with ops.anchors.feature_shape.
"""

from __future__ import annotations

from collections.abc import Callable, Sequence
from typing import Any

import flax.linen as nn
import jax.numpy as jnp
from jax import lax

ModuleDef = Any


class StemConv(nn.Module):
    """The 7x7/2 stem conv, optionally computed space-to-depth.

    The plain stem is the worst op on the MXU: a 3-input-channel conv runs
    the 128-wide systolic array at ~2% occupancy (profiled 7.5 TFLOP/s vs
    ~180 for the heads' 256-channel convs).  ``space_to_depth`` is the
    MLPerf-ResNet reformulation: fold each 2x2 pixel block into channels
    (3 → 12) and convolve 4x4/1 with an exactly-equivalent reshaped kernel —
    identical math, 4x the contraction depth, one H-fold transpose of the
    (B, H, W, 3) tensor (the W fold is layout-free; see the fold comment).

    The parameter keeps the canonical ``(7, 7, C, 64)`` layout either way, so
    checkpoints and the torch-weight importer (models/import_weights.py) are
    mode-independent; the kernel reshape is 9k elements and folds into XLA's
    constant/weight preprocessing.

    ``block=4`` folds 4x4 tiles (48-channel contraction, both MXU sides well
    fed) and emits each block's two stride-2 outputs as channels, unfolded
    depth-to-space after.  MEASURED (v5e-1, flagship b8 train step): 140.9 ms
    vs 135.1 ms for ``block=2`` — the zero-padded kernel does 2.9x the MACs
    and the (B, H/4, W/4, 256) output shuffle is extra bandwidth, which
    together outweigh the packing gain.  Kept as an exact, tested
    reformulation in case future hardware shifts the tradeoff; ``block=2``
    stays the default.
    """

    features: int = 64
    space_to_depth: bool = False
    # Fold size when space_to_depth: 2 folds 2x2 pixel blocks (12-channel
    # contraction), 4 folds 4x4 blocks (48 channels, both MXU sides well fed
    # — measured numbers in the class docstring) and emits both stride-2
    # outputs of each block as channels, unfolded depth-to-space after.
    block: int = 2
    dtype: jnp.dtype = jnp.bfloat16

    # When True (and the h2w4 lowering applies), return the conv output in
    # its native packed layout (B, H/2, W/4, (u, f)) — u = the two stride-2
    # W outputs per block, u-MAJOR — instead of unfolding to
    # (B, H/2, W/2, f).  The unfold is a lane retile (128 -> 64) that XLA
    # pays as ~4 copies fwd+bwd (~5 ms/step profiled); the ResNet wiring
    # instead runs norm/relu packed and lets the maxpool consume the packed
    # layout directly (maxpool_packed_w).
    packed_output: bool = False

    def _h2w4(self, x: jnp.ndarray, kernel: jnp.ndarray) -> jnp.ndarray:
        """block=2 stem computed as an H-fold-2 / W-fold-4 block conv.

        Same math as the 2x2 fold (one zero-led kernel regather), but the
        conv runs at (4, 3, 8c, 128) instead of (4, 4, 4c, 64): 24 input
        channels / 128 output channels fill the MXU far better than 12/64,
        which outweighs the 1.5x MAC redundancy of the wider zero-padded
        taps.  MEASURED (v5e-1, flagship shapes, fwd+bwd in isolation):
        4.4 ms vs 9.1 ms for the 2x2 form — and unlike the 4x4 fold
        (measured end-to-end negative, class docstring) BOTH W-side
        reshapes stay free: the W input fold because W-slots are
        channel-major, and the W output unfold because the two stride-2
        outputs of each block are emitted u-MAJOR ahead of the feature
        channels.  Only the H fold moves data (the same single transpose
        the 2x2 form pays).

        Derivation (torch geometry, per dim: out[o] = Σ_t w[t]·x[2o+t-3]):
        H: x row 2j+t-3 = 2(j+β)+r → t = 2β+r+3, β ∈ {-2..1} → 4 taps,
        pad (2, 1).  W: with o = 2J+u (u ∈ {0,1} emitted as channels) and
        x col 4(J+β)+r → t = 4β+r-2u+3, β ∈ {-1..1} → 3 taps, pad (1, 1).
        Invalid t gathers a zero row (index 7 of the zero-padded kernel).
        """
        b, h, w, c_in = x.shape
        f = self.features
        x = x.reshape(b, h // 2, 2, w, c_in)
        x = x.transpose(0, 1, 3, 2, 4)  # the one real data movement
        x = x.reshape(b, h // 2, w // 4, 8 * c_in)  # (p_w, p_h, c): free
        dy = jnp.arange(4)
        rh = jnp.arange(2)
        t_h = 2 * (dy[:, None] - 2) + rh[None, :] + 3  # (dy, rh)
        dx = jnp.arange(3)
        rw = jnp.arange(4)
        u = jnp.arange(2)
        t_w = (
            4 * (dx[:, None, None] - 1)
            + rw[None, :, None]
            - 2 * u[None, None, :]
            + 3
        )  # (dx, rw, u)
        t_h = jnp.where((t_h >= 0) & (t_h <= 6), t_h, 7)
        t_w = jnp.where((t_w >= 0) & (t_w <= 6), t_w, 7)
        kp = jnp.pad(kernel, ((0, 1), (0, 1), (0, 0), (0, 0)))  # (8, 8, c, f)
        kg = kp[
            t_h[:, :, None, None, None], t_w[None, None, :, :, :]
        ]  # (dy, rh, dx, rw, u, c, f)
        kg = kg.transpose(0, 2, 3, 1, 5, 4, 6)  # (dy, dx, rw, rh, c, u, f)
        k2 = kg.reshape(4, 3, 8 * c_in, 2 * f)
        y = lax.conv_general_dilated(
            x,
            k2.astype(self.dtype),
            window_strides=(1, 1),
            padding=((2, 1), (1, 1)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )  # (b, h/2, w/4, (u, f))
        if self.packed_output:
            return y
        return y.reshape(b, h // 2, w // 2, f)  # W unfold (lane retile)

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = x.astype(self.dtype)
        c_in = x.shape[-1]
        kernel = self.param(
            "kernel",
            nn.initializers.lecun_normal(),
            (7, 7, c_in, self.features),
            jnp.float32,
        )
        dn = ("NHWC", "HWIO", "NHWC")
        if not self.space_to_depth:
            # Symmetric (3, 3) padding — torchvision's conv1 geometry, so
            # imported pretrained weights see the exact sampling grid they
            # were trained with (XLA's SAME rule pads (2, 3) on even dims,
            # shifting every output half a tap).  Output stays ceil(d/2)
            # for every input parity.
            return lax.conv_general_dilated(
                x,
                kernel.astype(self.dtype),
                window_strides=(2, 2),
                padding=((3, 3), (3, 3)),
                dimension_numbers=dn,
            )

        b, h, w, _ = x.shape
        if h % self.block or w % self.block:
            raise ValueError(
                f"space_to_depth({self.block}) stem needs H, W divisible by "
                f"{self.block}; got {(h, w)}"
            )
        if self.block == 2 and w % 4 == 0:
            return self._h2w4(x, kernel)
        if self.packed_output:
            raise ValueError(
                "packed_output requires the h2w4 lowering "
                f"(block=2 and W % 4 == 0; got block={self.block}, W={w})"
            )
        # Input: fold block x block pixel tiles into channels.  Channel order
        # is (p_w, p_h, c) — W-slot MAJOR — because that order makes the W
        # fold a FREE reshape: only the H fold needs a real transpose.  The
        # naive (p_h, p_w, c) reshape/transpose/reshape lowered to ~3.7 ms of
        # minor-dim layout copies per b8 step (HLO copy.245/246/248, round-3
        # profile); a strided-slice+concat form measured worse still
        # (138.4 vs 131.8 ms/step).  Kernel folds below use the same order.
        s = self.block
        x = x.reshape(b, h // s, s, w, c_in)
        x = x.transpose(0, 1, 3, 2, 4)  # the one real data movement
        x = x.reshape(b, h // s, w // s, s * s * c_in)  # W fold: free
        if s == 2:
            # Kernel: pad 7→8 taps (LEADING zero), split each spatial dim
            # into (block, within-block) and fold within-block into input
            # channels in the SAME (p_w, p_h, c) order as the input fold.
            # With the torch geometry out[j] = Σ_t x[2j+t-3]·w[t]; writing
            # the x index as 2(j+β)+r gives tap u = 2β+r+4 into the zero-led
            # 8-kernel — a 4-tap block conv over β ∈ {-2..1} → padding (2, 1).
            k = jnp.pad(kernel, ((1, 0), (1, 0), (0, 0), (0, 0)))
            k = k.reshape(4, 2, 4, 2, c_in, self.features)
            k = k.transpose(0, 2, 3, 1, 4, 5).reshape(
                4, 4, 4 * c_in, self.features
            )
            return lax.conv_general_dilated(
                x,
                k.astype(self.dtype),
                window_strides=(1, 1),
                padding=((2, 1), (2, 1)),
                dimension_numbers=dn,
            )
        if s != 4:
            raise ValueError(f"space_to_depth block must be 2 or 4, got {s}")
        # 4x4 fold: each block carries TWO stride-2 outputs per spatial dim,
        # emitted as extra output channels and unfolded depth-to-space below.
        # With the torch (3, 3) padding the stride-2 conv is
        # out[i] = Σ_t w[t]·x[2i+t-3] (t = 0..6); writing i = 2j+u
        # (u ∈ {0,1} within block j) and x-index = 4(j+β)+r (β block tap,
        # r ∈ 0..3 within block) gives
        #   t = 4β + r - 2u + 3,
        # a 3-tap block conv (β ∈ {-1,0,1}, padding (1,1)) whose folded
        # kernel gathers the original tap t where valid and zero elsewhere.
        beta = jnp.arange(3) - 1  # block taps
        r = jnp.arange(4)
        u = jnp.arange(2)
        t = (4 * beta[:, None, None] + r[None, :, None]
             - 2 * u[None, None, :] + 3)  # (β, r, u)
        valid = (t >= 0) & (t <= 6)
        t = jnp.where(valid, t, 7)  # 7 = the zero-padded tap
        kp = jnp.pad(kernel, ((0, 1), (0, 1), (0, 0), (0, 0)))  # (8,8,c,f)
        # Gather → (βh, rh, uh, βw, rw, uw, c, f), then order in-channels as
        # (rw, rh, c) [matching the input fold] and out-channels as
        # (uh, uw, f) [matching the depth-to-space unfold].
        k = kp[t[:, :, :, None, None, None], t[None, None, None, :, :, :]]
        k = k.transpose(0, 3, 4, 1, 6, 2, 5, 7).reshape(
            3, 3, 16 * c_in, 4 * self.features
        )
        y = lax.conv_general_dilated(
            x,
            k.astype(self.dtype),
            window_strides=(1, 1),
            padding=((1, 1), (1, 1)),
            dimension_numbers=dn,
        )
        # Depth-to-space: (B, h/4, w/4, (uh, uw, f)) → (B, h/2, w/2, f).
        bh, bw = h // 4, w // 4
        y = y.reshape(b, bh, bw, 2, 2, self.features)
        y = y.transpose(0, 1, 3, 2, 4, 5).reshape(
            b, 2 * bh, 2 * bw, self.features
        )
        return y


# --- Width-packing: run narrow-channel stages with W-pairs folded into
# channels ------------------------------------------------------------------
#
# Stage2's C=64 contractions under-fill the v5e MXU's 128 lanes on BOTH
# matmul sides (profiled ~30 TFLOP/s vs ~188 for the 256-channel heads —
# PARITY.md attribution table; the single worst slice of the step at
# ~23 ms).  Folding each pair of adjacent W positions into channels makes
# every stage2 tensor 128-channel and every conv a 128x128-block
# contraction: kernels become block-structured (1x1 -> block-diagonal over
# the two W slots; 3x3 -> a 3-tap conv over packed columns whose taps
# gather the original taps, half the blocks structurally zero).  The
# hardware does 2x the MACs (the zero blocks) at ~4x the lane occupancy.
# MEASURED NEGATIVE end-to-end on v5e at the flagship bucket (58.3 vs
# 60.7 imgs/s, b8): profiling shows stage2 is mostly HBM-bandwidth-bound
# (~513 GB/s on 11.9 GB/step), so the extra MACs outweigh the occupancy
# win; only its three fwd 3x3 convs (~2.5 ms at 48 TF/s) are lane-bound.
# Kept OFF by default as an exact, tested reformulation (PARITY.md r3).
# Math is IDENTICAL: same sums, reordered; params keep their canonical
# shapes, so checkpoints/imports are layout-independent.
#
# Packed channel order is (c, u) — logical channel MAJOR, w-slot minor — so
# GroupNorm's contiguous channel groups stay contiguous after packing and
# per-channel affines broadcast with a plain reshape.


def _pack_w(x: jnp.ndarray) -> jnp.ndarray:
    """(B, H, W, C) → (B, H, W/2, 2C), packed channel index = c*2 + u."""
    b, h, w, c = x.shape
    return (
        x.reshape(b, h, w // 2, 2, c)
        .transpose(0, 1, 2, 4, 3)
        .reshape(b, h, w // 2, 2 * c)
    )


def _unpack_w(x: jnp.ndarray) -> jnp.ndarray:
    """Inverse of :func:`_pack_w`."""
    b, h, wp, c2 = x.shape
    c = c2 // 2
    return (
        x.reshape(b, h, wp, c, 2).transpose(0, 1, 2, 4, 3).reshape(b, h, 2 * wp, c)
    )


def _pack_kernel_1x1(k: jnp.ndarray) -> jnp.ndarray:
    """(1, 1, ci, co) → (1, 1, 2ci, 2co) block-diagonal over the w slot."""
    cin, cout = k.shape[2], k.shape[3]
    eye = jnp.eye(2, dtype=k.dtype)
    kp = k[:, :, :, None, :, None] * eye[None, None, None, :, None, :]
    return kp.reshape(1, 1, 2 * cin, 2 * cout)


def _pack_kernel_3x3(k: jnp.ndarray) -> jnp.ndarray:
    """(3, 3, ci, co) → (3, 3, 2ci, 2co) packed-column taps.

    Output w index 2j+u reads input 2j+u+dw = 2(j+β)+r, so packed tap β
    carries original tap dw = 2β + r - u where that lands in {-1, 0, 1}
    and zero elsewhere (gathered via a zero-padded 4th tap).
    """
    cin, cout = k.shape[2], k.shape[3]
    beta = jnp.arange(3) - 1
    r = jnp.arange(2)
    u = jnp.arange(2)
    t = 2 * beta[:, None, None] + r[None, :, None] - u[None, None, :] + 1
    tw = jnp.where((t >= 0) & (t <= 2), t, 3)  # (β, r, u); 3 = zero tap
    kpad = jnp.pad(k, ((0, 0), (0, 1), (0, 0), (0, 0)))  # (3, 4, ci, co)
    kp = kpad[:, tw]  # (dh, β, r, u, ci, co)
    kp = kp.transpose(0, 1, 4, 2, 5, 3)  # (dh, β, ci, r, co, u)
    return kp.reshape(3, 3, 2 * cin, 2 * cout)


class PackedConv(nn.Module):
    """Stride-1 conv on the width-packed layout; canonical param shape.

    Declares ``kernel`` as the logical (k, k, cin, cout) — identical tree
    to ``nn.Conv`` — and runs the packed-block equivalent; the kernel
    repack is a few-KB gather XLA folds into weight preprocessing.
    """

    features: int
    kernel_size: int  # 1 or 3
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        cin = x.shape[-1] // 2
        k = self.param(
            "kernel",
            nn.initializers.lecun_normal(),
            (self.kernel_size, self.kernel_size, cin, self.features),
            jnp.float32,
        )
        if self.kernel_size == 1:
            kp, pad = _pack_kernel_1x1(k), (0, 0)
        elif self.kernel_size == 3:
            kp, pad = _pack_kernel_3x3(k), (1, 1)
        else:
            raise ValueError(f"PackedConv supports k in (1, 3), got {self.kernel_size}")
        return lax.conv_general_dilated(
            x,
            kp.astype(self.dtype),
            window_strides=(1, 1),
            padding=(pad, pad),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )


class PackedGroupNorm(nn.Module):
    """GroupNorm(32) on the packed layout, exact w.r.t. the unpacked op.

    Stats for a logical-channel group must pool BOTH w slots of its
    channels.  ``slot_major`` selects the packing order: False = (c, u)
    channel-major (the pack_width stage layout), True = (u, c) slot-major
    (the h2w4 packed stem layout) — same math, different unpack reshape.
    Params are the logical (C,) scale/bias — same tree as ``nn.GroupNorm``.
    """

    num_groups: int = 32
    epsilon: float = 1e-6
    dtype: jnp.dtype = jnp.bfloat16
    slot_major: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        b, h, wp, c2 = x.shape
        c = c2 // 2
        scale = self.param("scale", nn.initializers.ones_init(), (c,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros_init(), (c,), jnp.float32)
        g = self.num_groups
        xf = x.astype(jnp.float32)
        if self.slot_major:
            xf = xf.reshape(b, h, wp, 2, g, c // g)
            pool_axes, aff = (1, 2, 3, 5), (1, 1, 1, 1, g, c // g)
        else:
            xf = xf.reshape(b, h, wp, g, c // g, 2)
            pool_axes, aff = (1, 2, 4, 5), (1, 1, 1, g, c // g, 1)
        mean = xf.mean(axis=pool_axes, keepdims=True)
        # use_fast_variance formula, as flax GroupNorm computes it.
        var = (xf * xf).mean(axis=pool_axes, keepdims=True) - mean * mean
        y = (xf - mean) * lax.rsqrt(var + self.epsilon)
        y = y * scale.reshape(aff) + bias.reshape(aff)
        return y.reshape(b, h, wp, c2).astype(self.dtype)


class PackedBatchNorm(nn.Module):
    """BatchNorm on the packed layout; same variable tree as ``nn.BatchNorm``.

    Batch statistics pool over (B, H, Wp, slot) — exactly the unpacked
    (B, H, W) reduction.  ``use_running_average`` covers both frozen_bn
    (always) and plain bn at eval; train-mode bn updates the running stats
    with the same 0.9 momentum as the unpacked layer.  ``slot_major`` as
    in :class:`PackedGroupNorm`.
    """

    use_running_average: bool
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: jnp.dtype = jnp.bfloat16
    slot_major: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        b, h, wp, c2 = x.shape
        c = c2 // 2
        scale = self.param("scale", nn.initializers.ones_init(), (c,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros_init(), (c,), jnp.float32)
        ra_mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros((c,), jnp.float32)
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda: jnp.ones((c,), jnp.float32)
        )
        xf = x.astype(jnp.float32)
        if self.slot_major:
            xf = xf.reshape(b, h, wp, 2, c)
            pool_axes, chan = (0, 1, 2, 3), slice(None)
        else:
            xf = xf.reshape(b, h, wp, c, 2)
            pool_axes, chan = (0, 1, 2, 4), (slice(None), None)
        if self.use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            mean = xf.mean(axis=pool_axes)
            var = (xf * xf).mean(axis=pool_axes) - mean * mean
            if not self.is_initializing():
                ra_mean.value = (
                    self.momentum * ra_mean.value + (1 - self.momentum) * mean
                )
                ra_var.value = (
                    self.momentum * ra_var.value + (1 - self.momentum) * var
                )
        y = (xf - mean[chan]) * lax.rsqrt(var[chan] + self.epsilon)
        y = y * scale[chan] + bias[chan]
        return y.reshape(b, h, wp, c2).astype(self.dtype)


# --- Packed-stem maxpool ----------------------------------------------------
#
# The h2w4 stem emits (B, H/2, W/4, (u, f)) with the W slot u MAJOR (that is
# what makes its kernel fold free); PackedGroupNorm/PackedBatchNorm handle
# that order via slot_major=True, and maxpool_packed_w consumes the packed
# layout directly so the 128->64 lane retile of an explicit unfold never
# happens.


def maxpool_packed_w(x: jnp.ndarray) -> jnp.ndarray:
    """3x3/s2 maxpool with (1, 1) -inf padding, consuming the u-major
    packed stem layout and emitting the UNPACKED pooled tensor.

    H first: a native 3x1/s2 reduce_window on the packed tensor (its VJP
    is the efficient 1-D select_and_scatter).  Then W on the QUARTER-SIZE
    result: logical cols w = 2J + u, and pooled col o reads w in
    {2o-1, 2o, 2o+1} = (J=o-1, u=1), (J=o, u=0), (J=o, u=1) — two channel
    halves plus one shifted slice (lax.pad with a negative edge), pure
    lane ops.  Forward matches
    ``nn.max_pool(x_unfolded, (3, 3), (2, 2), ((1, 1), (1, 1)))`` exactly
    (pinned by a unit test).

    Backward is plain autodiff: first-max rows along H, JAX's half/half
    tie split along W — a deliberate, documented subgradient divergence
    from the 2-D select_and_scatter's row-major first-max (ties only;
    both are valid, deterministic, and identical across shards).  The
    exact-routing custom VJP was measured SLOWER either way it was
    decomposed (W-first: ~4 ms/step of select traffic at full height);
    this H-first form measured 6.2 ms vs 6.6 for the unpacked
    nn.max_pool fwd+bwd in isolation at the flagship bucket.
    """
    y = lax.reduce_window(
        x,
        -jnp.inf,
        lax.max,
        (1, 3, 1, 1),
        (1, 2, 1, 1),
        ((0, 0), (1, 1), (0, 0), (0, 0)),
    )  # (b, h/2 -> h/4 rows, w4, (u, f))
    f = y.shape[-1] // 2
    u0 = y[..., :f]
    u1 = y[..., f:]
    # Shift right one block: u1_left[J] = u1[J-1], -inf into the new col.
    u1_left = lax.pad(
        u1,
        jnp.asarray(-jnp.inf, y.dtype),
        ((0, 0, 0), (0, 0, 0), (1, -1, 0), (0, 0, 0)),
    )
    return jnp.maximum(jnp.maximum(u1_left, u0), u1)


class NormFactory:
    """Builds the configured norm layer; see module docstring for options."""

    def __init__(self, kind: str, dtype: jnp.dtype):
        if kind not in ("gn", "bn", "frozen_bn"):
            raise ValueError(f"unknown norm kind: {kind!r}")
        self.kind = kind
        self.dtype = dtype

    def __call__(self, name: str, train: bool) -> Callable:
        if self.kind == "gn":
            return nn.GroupNorm(
                num_groups=32, dtype=self.dtype, name=name, param_dtype=jnp.float32
            )
        use_running = (self.kind == "frozen_bn") or (not train)
        return nn.BatchNorm(
            use_running_average=use_running,
            momentum=0.9,
            dtype=self.dtype,
            param_dtype=jnp.float32,
            name=name,
        )

    def packed(self, name: str, train: bool, slot_major: bool = False) -> Callable:
        """The same norm, applied on a width-packed layout (same params)."""
        if self.kind == "gn":
            return PackedGroupNorm(
                dtype=self.dtype, slot_major=slot_major, name=name
            )
        use_running = (self.kind == "frozen_bn") or (not train)
        return PackedBatchNorm(
            use_running_average=use_running,
            dtype=self.dtype,
            slot_major=slot_major,
            name=name,
        )


class BottleneckBlock(nn.Module):
    """1x1 → 3x3(stride) → 1x1(x4) with projection shortcut on shape change."""

    filters: int
    stride: int
    norm: NormFactory
    dtype: jnp.dtype = jnp.bfloat16
    packed: bool = False  # width-packed layout (stride must be 1)

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        # Symmetric (k//2) padding, torchvision's geometry: identical to
        # SAME for stride 1, but for stride 2 on even dims SAME pads (0, 1)
        # — a one-pixel grid shift that would misalign imported pretrained
        # features.  Output sizes are ceil(d/s) either way.
        if self.packed:
            if self.stride != 1:
                raise ValueError("packed bottleneck blocks require stride 1")
            conv = lambda f, k, s, name: PackedConv(  # noqa: E731
                features=f, kernel_size=k, dtype=self.dtype, name=name
            )
            norm_for = lambda name: self.norm.packed(name, train)  # noqa: E731
        else:
            conv = lambda f, k, s, name: nn.Conv(  # noqa: E731
                f,
                (k, k),
                strides=(s, s),
                padding=((k // 2, k // 2), (k // 2, k // 2)),
                use_bias=False,
                dtype=self.dtype,
                param_dtype=jnp.float32,
                name=name,
            )
            norm_for = lambda name: self.norm(name, train)  # noqa: E731
        residual = x
        y = conv(self.filters, 1, 1, "conv1")(x)
        y = norm_for("norm1")(y)
        y = nn.relu(y)
        y = conv(self.filters, 3, self.stride, "conv2")(y)
        y = norm_for("norm2")(y)
        y = nn.relu(y)
        y = conv(self.filters * 4, 1, 1, "conv3")(y)
        y = norm_for("norm3")(y)
        if residual.shape != y.shape:
            residual = conv(self.filters * 4, 1, self.stride, "proj")(x)
            residual = norm_for("proj_norm")(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    """ResNet exposing {"c3", "c4", "c5"} (strides 8/16/32)."""

    stage_sizes: Sequence[int]
    norm_kind: str = "gn"
    dtype: jnp.dtype = jnp.bfloat16
    stem: str = "conv"  # "conv" | "space_to_depth" | "space_to_depth4"
    # Run stage2 (the C=64 stage — PARITY.md's worst MXU slice) with W-pairs
    # packed into channels; math-identical, same param tree (see the
    # width-packing block above).  Needs stage2 width (ceil(W_img/4)) even.
    pack_width: bool = False
    # Stem downsample: "max" is the canonical 3x3/2 maxpool.  "avg" swaps in
    # an avg pool of the same geometry — a DIAGNOSTIC configuration whose
    # gradient is linear and therefore tie-free: maxpool backward routes
    # each window's cotangent to its first max, and which element wins a
    # tie is partition-dependent under GSPMD spatial sharding
    # (tests/distributed/test_spatial_train.py uses this knob to prove the
    # spatial step's gradient divergence lives ENTIRELY in the pool).
    stem_pool: str = "max"  # "max" | "avg"

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> dict[str, jnp.ndarray]:
        if self.stem not in ("conv", "space_to_depth", "space_to_depth4"):
            raise ValueError(f"unknown stem: {self.stem!r}")
        if self.stem_pool not in ("max", "avg"):
            raise ValueError(f"unknown stem_pool: {self.stem_pool!r}")
        if self.stem_pool == "avg" and self.stem != "conv":
            raise ValueError(
                "stem_pool='avg' (the tie-free diagnostic pool) is only "
                "supported with stem='conv' — the packed stem layouts bake "
                "in the maxpool (maxpool_packed_w)"
            )
        norm = NormFactory(self.norm_kind, self.dtype)
        x = x.astype(self.dtype)
        # The h2w4 stem lowering keeps its output packed (B, H/2, W/4,
        # (u, f)) and norm/relu/maxpool consume that layout: unfolding
        # first costs a 128->64 lane retile XLA pays as ~4 full copies
        # fwd+bwd (~5 ms/step profiled at the flagship bucket).
        packed_stem = (
            self.stem == "space_to_depth"
            and x.shape[1] % 2 == 0
            and x.shape[2] % 4 == 0
        )
        x = StemConv(
            features=64,
            space_to_depth=self.stem != "conv",
            block=4 if self.stem == "space_to_depth4" else 2,
            dtype=self.dtype,
            packed_output=packed_stem,
            name="stem_conv",
        )(x)
        if packed_stem:
            x = norm.packed("stem_norm", train, slot_major=True)(x)
            x = nn.relu(x)
            x = maxpool_packed_w(x)
        else:
            x = norm("stem_norm", train)(x)
            x = nn.relu(x)
            if self.stem_pool == "avg":
                # Tie-free diagnostic downsample (see stem_pool field doc).
                x = nn.avg_pool(
                    x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1))
                )
            else:
                # Symmetric (1, 1) padding (torch geometry; SAME would pad
                # (0, 1) on even dims).  -inf pad so padding never wins the
                # max.
                x = nn.max_pool(
                    x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1))
                )

        features: dict[str, jnp.ndarray] = {}
        filters = 64
        for stage, num_blocks in enumerate(self.stage_sizes):
            stride = 1 if stage == 0 else 2
            packed = self.pack_width and stage == 0  # all-stride-1 C=64 stage
            if packed:
                if x.shape[2] % 2:
                    raise ValueError(
                        f"pack_width needs an even stage2 width; got "
                        f"{x.shape[2]} (make W divisible by 8)"
                    )
                x = _pack_w(x)
            for block in range(num_blocks):
                x = BottleneckBlock(
                    filters=filters,
                    stride=stride if block == 0 else 1,
                    norm=norm,
                    dtype=self.dtype,
                    packed=packed,
                    name=f"stage{stage + 2}_block{block}",
                )(x, train=train)
            if packed:
                x = _unpack_w(x)
            if stage >= 1:  # C3 at stride 8, C4 at 16, C5 at 32
                features[f"c{stage + 2}"] = x
            filters *= 2
        return features


def resnet50(
    norm_kind: str = "gn",
    dtype: jnp.dtype = jnp.bfloat16,
    stem: str = "conv",
    pack_width: bool = False,
) -> ResNet:
    return ResNet(
        stage_sizes=(3, 4, 6, 3),
        norm_kind=norm_kind,
        dtype=dtype,
        stem=stem,
        pack_width=pack_width,
    )
