"""ResNet backbone (v1.5 bottleneck) exposing C3, C4, C5 feature maps.

Parity target: keras-retinanet's ResNet-50 backbone (SURVEY.md M2,
``models/resnet.py`` + the keras-resnet dependency), which feeds C3..C5 into
the FPN and freezes BatchNorm during detection fine-tuning.

TPU-first design:
- NHWC layout (XLA:TPU's native conv layout), bfloat16 activations with
  float32 params by default — convs hit the MXU in bf16.
- Norm is pluggable:
  * ``"gn"`` (default): GroupNorm(32) — batch-size independent, no mutable
    state, the right choice for from-scratch training in an air-gapped env
    (SURVEY.md §7.3 hard part 5);
  * ``"bn"``: BatchNorm with running stats (mutable ``batch_stats``);
  * ``"frozen_bn"``: running-stats-only BatchNorm (never updates), matching
    the reference's frozen-BN fine-tuning recipe when pretrained weights are
    supplied.
- Strided 3x3 in the bottleneck's middle conv (v1.5), symmetric torch-style
  padding (k//2 each side) so imported torchvision weights see the exact
  sampling grid they were trained with; spatial dims still follow
  ceil(H/stride) — consistent with ops.anchors.feature_shape.
"""

from __future__ import annotations

from collections.abc import Callable, Sequence
from typing import Any

import flax.linen as nn
import jax.numpy as jnp
from jax import lax

ModuleDef = Any


class StemConv(nn.Module):
    """The 7x7/2 stem conv, optionally computed space-to-depth.

    The plain stem is the worst op on the MXU: a 3-input-channel conv runs
    the 128-wide systolic array at ~2% occupancy (profiled 7.5 TFLOP/s vs
    ~180 for the heads' 256-channel convs).  ``space_to_depth`` is the
    MLPerf-ResNet reformulation: fold each 2x2 pixel block into channels
    (3 → 12) and convolve 4x4/1 with an exactly-equivalent reshaped kernel —
    identical math, 4x the contraction depth, no layout copies of the
    (B, H, W, 3) tensor.

    The parameter keeps the canonical ``(7, 7, C, 64)`` layout either way, so
    checkpoints and the torch-weight importer (models/import_weights.py) are
    mode-independent; the kernel reshape is 9k elements and folds into XLA's
    constant/weight preprocessing.

    ``block=4`` folds 4x4 tiles (48-channel contraction, both MXU sides well
    fed) and emits each block's two stride-2 outputs as channels, unfolded
    depth-to-space after.  MEASURED (v5e-1, flagship b8 train step): 140.9 ms
    vs 135.1 ms for ``block=2`` — the zero-padded kernel does 2.9x the MACs
    and the (B, H/4, W/4, 256) output shuffle is extra bandwidth, which
    together outweigh the packing gain.  Kept as an exact, tested
    reformulation in case future hardware shifts the tradeoff; ``block=2``
    stays the default.
    """

    features: int = 64
    space_to_depth: bool = False
    # Fold size when space_to_depth: 2 folds 2x2 pixel blocks (12-channel
    # contraction), 4 folds 4x4 blocks (48 channels, both MXU sides well fed
    # — measured numbers in the class docstring) and emits both stride-2
    # outputs of each block as channels, unfolded depth-to-space after.
    block: int = 2
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = x.astype(self.dtype)
        c_in = x.shape[-1]
        kernel = self.param(
            "kernel",
            nn.initializers.lecun_normal(),
            (7, 7, c_in, self.features),
            jnp.float32,
        )
        dn = ("NHWC", "HWIO", "NHWC")
        if not self.space_to_depth:
            # Symmetric (3, 3) padding — torchvision's conv1 geometry, so
            # imported pretrained weights see the exact sampling grid they
            # were trained with (XLA's SAME rule pads (2, 3) on even dims,
            # shifting every output half a tap).  Output stays ceil(d/2)
            # for every input parity.
            return lax.conv_general_dilated(
                x,
                kernel.astype(self.dtype),
                window_strides=(2, 2),
                padding=((3, 3), (3, 3)),
                dimension_numbers=dn,
            )

        b, h, w, _ = x.shape
        if h % self.block or w % self.block:
            raise ValueError(
                f"space_to_depth({self.block}) stem needs H, W divisible by "
                f"{self.block}; got {(h, w)}"
            )
        # Input: fold block x block pixel tiles into channels, (p_h, p_w, c)
        # order.
        s = self.block
        x = x.reshape(b, h // s, s, w // s, s, c_in)
        x = x.transpose(0, 1, 3, 2, 4, 5).reshape(b, h // s, w // s, s * s * c_in)
        if s == 2:
            # Kernel: pad 7→8 taps (LEADING zero), split each spatial dim
            # into (block, within-block) and fold within-block into input
            # channels in the SAME (p_h, p_w, c) order.  With the torch
            # geometry out[j] = Σ_t x[2j+t-3]·w[t]; writing the x index as
            # 2(j+β)+r gives tap u = 2β+r+4 into the zero-led 8-kernel —
            # a 4-tap block conv over β ∈ {-2..1} → padding (2, 1).
            k = jnp.pad(kernel, ((1, 0), (1, 0), (0, 0), (0, 0)))
            k = k.reshape(4, 2, 4, 2, c_in, self.features)
            k = k.transpose(0, 2, 1, 3, 4, 5).reshape(
                4, 4, 4 * c_in, self.features
            )
            return lax.conv_general_dilated(
                x,
                k.astype(self.dtype),
                window_strides=(1, 1),
                padding=((2, 1), (2, 1)),
                dimension_numbers=dn,
            )
        if s != 4:
            raise ValueError(f"space_to_depth block must be 2 or 4, got {s}")
        # 4x4 fold: each block carries TWO stride-2 outputs per spatial dim,
        # emitted as extra output channels and unfolded depth-to-space below.
        # With the torch (3, 3) padding the stride-2 conv is
        # out[i] = Σ_t w[t]·x[2i+t-3] (t = 0..6); writing i = 2j+u
        # (u ∈ {0,1} within block j) and x-index = 4(j+β)+r (β block tap,
        # r ∈ 0..3 within block) gives
        #   t = 4β + r - 2u + 3,
        # a 3-tap block conv (β ∈ {-1,0,1}, padding (1,1)) whose folded
        # kernel gathers the original tap t where valid and zero elsewhere.
        beta = jnp.arange(3) - 1  # block taps
        r = jnp.arange(4)
        u = jnp.arange(2)
        t = (4 * beta[:, None, None] + r[None, :, None]
             - 2 * u[None, None, :] + 3)  # (β, r, u)
        valid = (t >= 0) & (t <= 6)
        t = jnp.where(valid, t, 7)  # 7 = the zero-padded tap
        kp = jnp.pad(kernel, ((0, 1), (0, 1), (0, 0), (0, 0)))  # (8,8,c,f)
        # Gather → (βh, rh, uh, βw, rw, uw, c, f), then order in-channels as
        # (rh, rw, c) [matching the input fold] and out-channels as
        # (uh, uw, f) [matching the depth-to-space unfold].
        k = kp[t[:, :, :, None, None, None], t[None, None, None, :, :, :]]
        k = k.transpose(0, 3, 1, 4, 6, 2, 5, 7).reshape(
            3, 3, 16 * c_in, 4 * self.features
        )
        y = lax.conv_general_dilated(
            x,
            k.astype(self.dtype),
            window_strides=(1, 1),
            padding=((1, 1), (1, 1)),
            dimension_numbers=dn,
        )
        # Depth-to-space: (B, h/4, w/4, (uh, uw, f)) → (B, h/2, w/2, f).
        bh, bw = h // 4, w // 4
        y = y.reshape(b, bh, bw, 2, 2, self.features)
        y = y.transpose(0, 1, 3, 2, 4, 5).reshape(
            b, 2 * bh, 2 * bw, self.features
        )
        return y


class NormFactory:
    """Builds the configured norm layer; see module docstring for options."""

    def __init__(self, kind: str, dtype: jnp.dtype):
        if kind not in ("gn", "bn", "frozen_bn"):
            raise ValueError(f"unknown norm kind: {kind!r}")
        self.kind = kind
        self.dtype = dtype

    def __call__(self, name: str, train: bool) -> Callable:
        if self.kind == "gn":
            return nn.GroupNorm(
                num_groups=32, dtype=self.dtype, name=name, param_dtype=jnp.float32
            )
        use_running = (self.kind == "frozen_bn") or (not train)
        return nn.BatchNorm(
            use_running_average=use_running,
            momentum=0.9,
            dtype=self.dtype,
            param_dtype=jnp.float32,
            name=name,
        )


class BottleneckBlock(nn.Module):
    """1x1 → 3x3(stride) → 1x1(x4) with projection shortcut on shape change."""

    filters: int
    stride: int
    norm: NormFactory
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        # Symmetric (k//2) padding, torchvision's geometry: identical to
        # SAME for stride 1, but for stride 2 on even dims SAME pads (0, 1)
        # — a one-pixel grid shift that would misalign imported pretrained
        # features.  Output sizes are ceil(d/s) either way.
        conv = lambda f, k, s, name: nn.Conv(  # noqa: E731
            f,
            (k, k),
            strides=(s, s),
            padding=((k // 2, k // 2), (k // 2, k // 2)),
            use_bias=False,
            dtype=self.dtype,
            param_dtype=jnp.float32,
            name=name,
        )
        residual = x
        y = conv(self.filters, 1, 1, "conv1")(x)
        y = self.norm("norm1", train)(y)
        y = nn.relu(y)
        y = conv(self.filters, 3, self.stride, "conv2")(y)
        y = self.norm("norm2", train)(y)
        y = nn.relu(y)
        y = conv(self.filters * 4, 1, 1, "conv3")(y)
        y = self.norm("norm3", train)(y)
        if residual.shape != y.shape:
            residual = conv(self.filters * 4, 1, self.stride, "proj")(x)
            residual = self.norm("proj_norm", train)(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    """ResNet exposing {"c3", "c4", "c5"} (strides 8/16/32)."""

    stage_sizes: Sequence[int]
    norm_kind: str = "gn"
    dtype: jnp.dtype = jnp.bfloat16
    stem: str = "conv"  # "conv" | "space_to_depth" | "space_to_depth4"

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> dict[str, jnp.ndarray]:
        if self.stem not in ("conv", "space_to_depth", "space_to_depth4"):
            raise ValueError(f"unknown stem: {self.stem!r}")
        norm = NormFactory(self.norm_kind, self.dtype)
        x = x.astype(self.dtype)
        x = StemConv(
            features=64,
            space_to_depth=self.stem != "conv",
            block=4 if self.stem == "space_to_depth4" else 2,
            dtype=self.dtype,
            name="stem_conv",
        )(x)
        x = norm("stem_norm", train)(x)
        x = nn.relu(x)
        # Symmetric (1, 1) padding (torch geometry; SAME would pad (0, 1)
        # on even dims).  -inf pad so padding never wins the max.
        x = nn.max_pool(
            x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1))
        )

        features: dict[str, jnp.ndarray] = {}
        filters = 64
        for stage, num_blocks in enumerate(self.stage_sizes):
            stride = 1 if stage == 0 else 2
            for block in range(num_blocks):
                x = BottleneckBlock(
                    filters=filters,
                    stride=stride if block == 0 else 1,
                    norm=norm,
                    dtype=self.dtype,
                    name=f"stage{stage + 2}_block{block}",
                )(x, train=train)
            if stage >= 1:  # C3 at stride 8, C4 at 16, C5 at 32
                features[f"c{stage + 2}"] = x
            filters *= 2
        return features


def resnet50(
    norm_kind: str = "gn",
    dtype: jnp.dtype = jnp.bfloat16,
    stem: str = "conv",
) -> ResNet:
    return ResNet(
        stage_sizes=(3, 4, 6, 3), norm_kind=norm_kind, dtype=dtype, stem=stem
    )
