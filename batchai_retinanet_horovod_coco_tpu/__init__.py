"""TPU-native RetinaNet training framework.

A ground-up JAX/XLA rebuild of the capability surface of
``msalvaris/batchai_retinanet_horovod_coco`` (RetinaNet ResNet-50-FPN on COCO,
Horovod data-parallel on Azure Batch AI), re-designed TPU-first:

- the Keras graph + Horovod ``DistributedOptimizer`` allreduce become ONE
  jit-compiled SPMD train step with ``jax.lax.psum`` over a device mesh
  (see ``parallel/`` and ``train/step.py``);
- host-side Cython anchor/IoU machinery (reference: keras-retinanet
  ``utils/compute_overlap.pyx``, ``utils/anchors.py``) becomes jit'd
  device-side ops (``ops/``);
- the CPU/GPU ``FilterDetections`` NMS layer becomes an on-device batched
  fixed-shape NMS (``ops/nms.py``, ``evaluate/detect.py``);
- pycocotools' C COCOeval becomes a self-contained numpy oracle with an
  optional C++ fast path (``evaluate/coco_eval.py``, ``native/``).

Reference structure is documented in /root/repo/SURVEY.md (the reference mount
was unavailable; citations therein are capability-level, anchored on
BASELINE.json).
"""

__version__ = "0.1.0"
