"""Invariant lint engine: shared machinery for project-wide AST passes.

The scaling, observability, and serving layers all rest on invariants that
were previously enforced only by convention plus two bespoke one-off audit
scripts (``scripts/audit_collectives.py``, ``scripts/audit_threads.py``):
bounded queues with shed reasons, crash-re-raise error contracts on every
long-lived thread, ONE monotonic clock, pure jit bodies, and collectives
that every host reaches unconditionally.  This module is the engine those
invariants are encoded against (the rules live in ``analysis/rules/``):

- **Rule registry** — rules self-register via the ``@register`` decorator;
  each rule is a pure function of one parsed file (``FileContext``) and
  returns ``Finding``s.  Rules are lexical AST passes: no imports of the
  linted code, no jax, stdlib only (this package must stay importable in
  jax-free processes, e.g. shm decode workers' CI checks).
- **Uniform suppression grammar** — ``# lint: <rule>[,<rule>...]: <why>``
  on the offending line or the line directly above it.  The rationale is
  REQUIRED non-empty: a suppression without a why, or naming an unknown
  rule, is itself a finding (rule name ``suppression``).  Suppressions are
  tracked, and unused ones are reported (informationally) in the JSON
  report so dead exemptions can be garbage-collected.
- **Committed baseline** — ``analysis/baseline.json`` grandfathers known
  findings by line-number-insensitive fingerprint ``(rule, path, snippet)``
  so new violations fail while old ones stay tracked.  The baseline is
  NON-GROWING by construction: a run fails on new findings AND on stale
  baseline entries (a fixed finding must be removed from the baseline via
  ``--update-baseline``, so the file only ever shrinks).
- **JSON report** — ``--json`` emits one machine-readable object (findings,
  new/grandfathered/stale split, suppressions used and unused, per-rule
  site counters proving the pass actually inspected constructs).

Run:
    python -m batchai_retinanet_horovod_coco_tpu.analysis            # lint
    python -m batchai_retinanet_horovod_coco_tpu.analysis --json
    python -m batchai_retinanet_horovod_coco_tpu.analysis --update-baseline

Wired into ``make lint`` / ``make check-static`` and tier-1
(tests/unit/test_lint.py::test_tree_is_clean).
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import tokenize
from collections import Counter
from typing import Callable, Iterable

#: Reserved rule name for engine-level suppression-grammar findings.
SUPPRESSION_RULE = "suppression"

_SUPPRESS_RE = re.compile(
    r"#\s*lint:\s*(?P<rules>[A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)\s*"
    r":(?P<why>.*)$"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one site."""

    rule: str
    path: str  # repo-relative (stable across checkouts; baseline key part)
    line: int  # 1-based; NOT part of the baseline key (lines drift)
    message: str
    snippet: str = ""  # stripped source line; the line-insensitive key part

    def key(self) -> tuple[str, str, str]:
        return (self.rule, self.path, self.snippet)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class Suppression:
    """A parsed ``# lint: <rules>: <why>`` comment."""

    line: int  # line the comment sits on
    applies_to: int  # code line it covers (own line, or first code line below)
    rules: tuple[str, ...]
    why: str
    used: bool = False


class FileContext:
    """Everything a rule may look at for one file: source, lines, AST,
    parsed suppressions, and where the file sits (package vs. script —
    some sub-checks only bind inside the package)."""

    def __init__(self, path: str, relpath: str, source: str,
                 in_package: bool = True):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.in_package = in_package
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.suppressions = _parse_suppressions(source, self.lines)
        self.stats: Counter = Counter()  # per-rule inspected-site counters

    # -- helpers rules share -------------------------------------------

    def snippet(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def finding(self, rule: str, line: int, message: str) -> Finding:
        return Finding(rule=rule, path=self.relpath, line=line,
                       message=message, snippet=self.snippet(line))

    def count(self, rule: str, n: int = 1) -> None:
        """Record that ``rule`` actually inspected ``n`` constructs here —
        the non-vacuity evidence the clean-tree test asserts on."""
        self.stats[rule] += n


def _parse_suppressions(source: str, lines: list[str]) -> list[Suppression]:
    """Tokenize-based comment scan (regex over raw lines would misfire on
    ``# lint:`` text inside string literals)."""
    comments: list[tuple[int, int, str]] = []  # (line, col, text)
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                comments.append((tok.start[0], tok.start[1], tok.string))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return []
    out = []
    for lineno, col, text in comments:
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        rules = tuple(r.strip() for r in m.group("rules").split(","))
        why = m.group("why").strip()
        # Own line if the comment trails code; otherwise the first
        # non-blank, non-comment line below it.
        own = lines[lineno - 1][:col].strip() if lineno <= len(lines) else ""
        applies_to = lineno
        if not own:
            j = lineno  # 0-based index of the next line
            while j < len(lines):
                stripped = lines[j].strip()
                if stripped and not stripped.startswith("#"):
                    applies_to = j + 1
                    break
                j += 1
        out.append(Suppression(line=lineno, applies_to=applies_to,
                               rules=rules, why=why))
    return out


# ---- rule registry -------------------------------------------------------

#: name -> (description, check(ctx) -> list[Finding])
RULES: dict[str, tuple[str, Callable[[FileContext], list[Finding]]]] = {}


def register(name: str, description: str):
    """Decorator: publish a rule under ``name`` in the registry."""

    def deco(fn: Callable[[FileContext], list[Finding]]):
        if name == SUPPRESSION_RULE:
            raise ValueError(f"rule name {name!r} is reserved")
        RULES[name] = (description, fn)
        return fn

    return deco


def _ensure_rules_loaded() -> None:
    # Import for the registration side effect; cheap and idempotent.
    from batchai_retinanet_horovod_coco_tpu.analysis import rules  # noqa: F401


# ---- per-file run --------------------------------------------------------

@dataclasses.dataclass
class FileResult:
    findings: list[Finding]
    suppressed: list[Finding]
    grammar_findings: list[Finding]  # bad suppression comments
    unused_suppressions: list[Suppression]
    stats: Counter


def _validate_rule_names(rule_names: Iterable[str] | None) -> list[str]:
    """Resolve a rule selection to known names; raise on typos (a typo'd
    ``--rule`` must not die with a raw KeyError deep in the walk)."""
    if rule_names is None:
        return sorted(RULES)
    names = list(rule_names)
    unknown = [n for n in names if n not in RULES]
    if unknown:
        raise ValueError(
            f"unknown rule(s) {unknown} (known: {sorted(RULES)})"
        )
    return names


def lint_source(path: str, relpath: str, source: str, *,
                rule_names: Iterable[str] | None = None,
                in_package: bool = True) -> FileResult:
    """Run the (selected) rules over one file's source."""
    _ensure_rules_loaded()
    names = _validate_rule_names(rule_names)
    try:
        ctx = FileContext(path, relpath, source, in_package=in_package)
    except SyntaxError as e:
        f = Finding(rule=SUPPRESSION_RULE, path=relpath, line=e.lineno or 0,
                    message=f"unparseable file: {e.msg}", snippet="")
        return FileResult([f], [], [], [], Counter())

    grammar: list[Finding] = []
    valid: list[Suppression] = []
    for sup in ctx.suppressions:
        bad = False
        if not sup.why:
            grammar.append(ctx.finding(
                SUPPRESSION_RULE, sup.line,
                "suppression missing rationale: '# lint: <rule>: <why>' "
                "requires a non-empty why",
            ))
            bad = True
        unknown = [r for r in sup.rules if r not in RULES]
        if unknown:
            grammar.append(ctx.finding(
                SUPPRESSION_RULE, sup.line,
                f"suppression names unknown rule(s) {unknown} "
                f"(known: {sorted(RULES)})",
            ))
            bad = True
        if not bad:
            valid.append(sup)

    raw: list[Finding] = []
    for name in names:
        _desc, fn = RULES[name]
        raw.extend(fn(ctx))

    kept: list[Finding] = []
    suppressed: list[Finding] = []
    for f in raw:
        sup = _match_suppression(valid, f)
        if sup is not None:
            sup.used = True
            suppressed.append(f)
        else:
            kept.append(f)
    unused = [s for s in valid if not s.used]
    return FileResult(kept, suppressed, grammar, unused, ctx.stats)


def _match_suppression(sups: list[Suppression], f: Finding):
    for sup in sups:
        if f.rule in sup.rules and f.line in (sup.line, sup.applies_to):
            return sup
    return None


# ---- tree walk -----------------------------------------------------------

PACKAGE_NAME = "batchai_retinanet_horovod_coco_tpu"

#: Directories under scripts/ that are NOT linted: xla_repros holds
#: filing-ready standalone upstream repro scripts whose text is frozen.
_SCRIPT_EXCLUDES = {"xla_repros", "__pycache__"}


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def iter_target_files(root: str | None = None):
    """Yield ``(abspath, relpath, in_package)`` for every linted file: the
    whole package tree, top-level driver scripts, and scripts/ (tests and
    the frozen xla repro scripts excluded)."""
    root = root or repo_root()
    pkg = os.path.join(root, PACKAGE_NAME)
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                p = os.path.join(dirpath, fn)
                yield p, os.path.relpath(p, root), True
    for fn in sorted(os.listdir(root)):
        if fn.endswith(".py"):
            yield os.path.join(root, fn), fn, False
    scripts = os.path.join(root, "scripts")
    if os.path.isdir(scripts):
        for dirpath, dirnames, filenames in os.walk(scripts):
            dirnames[:] = sorted(
                d for d in dirnames if d not in _SCRIPT_EXCLUDES
            )
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    p = os.path.join(dirpath, fn)
                    yield p, os.path.relpath(p, root), False


# ---- baseline ------------------------------------------------------------

def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


def load_baseline(path: str) -> Counter:
    """Multiset of grandfathered finding keys."""
    if not os.path.exists(path):
        return Counter()
    with open(path) as f:
        data = json.load(f)
    return Counter(
        (e["rule"], e["path"], e["snippet"]) for e in data.get("entries", [])
    )


def write_baseline(path: str, findings: list[Finding]) -> None:
    from batchai_retinanet_horovod_coco_tpu.utils.atomicio import (
        atomic_write_text,
    )

    entries = sorted(
        ({"rule": f.rule, "path": f.path, "snippet": f.snippet}
         for f in findings),
        key=lambda e: (e["path"], e["rule"], e["snippet"]),
    )
    atomic_write_text(
        path,
        json.dumps({"version": 1, "entries": entries}, indent=1,
                   sort_keys=True) + "\n",
    )


# ---- whole-run driver ----------------------------------------------------

def run(root: str | None = None, *, baseline_path: str | None = None,
        rule_names: Iterable[str] | None = None) -> dict:
    """Lint the tree, split findings against the baseline, return the
    report object (the ``--json`` payload)."""
    _ensure_rules_loaded()
    _validate_rule_names(rule_names)
    root = root or repo_root()
    baseline_path = baseline_path or default_baseline_path()
    baseline = load_baseline(baseline_path)

    findings: list[Finding] = []
    suppressed: list[Finding] = []
    grammar: list[Finding] = []
    unused: list[dict] = []
    stats: Counter = Counter()
    files_scanned = 0
    for path, relpath, in_pkg in iter_target_files(root):
        with open(path) as f:
            source = f.read()
        res = lint_source(path, relpath, source, rule_names=rule_names,
                          in_package=in_pkg)
        files_scanned += 1
        findings.extend(res.findings)
        suppressed.extend(res.suppressed)
        grammar.extend(res.grammar_findings)
        stats.update(res.stats)
        unused.extend(
            {"path": relpath, "line": s.line, "rules": list(s.rules),
             "why": s.why}
            for s in res.unused_suppressions
        )

    # Bad suppression comments are never baselinable: they fail outright.
    remaining = Counter(baseline)
    new: list[Finding] = []
    grandfathered: list[Finding] = []
    for f in findings:
        if remaining[f.key()] > 0:
            remaining[f.key()] -= 1
            grandfathered.append(f)
        else:
            new.append(f)
    stale = [
        {"rule": r, "path": p, "snippet": s, "missing": n}
        for (r, p, s), n in sorted(remaining.items()) if n > 0
    ]
    return {
        "root": root,
        "rules": sorted(rule_names) if rule_names else sorted(RULES),
        "files_scanned": files_scanned,
        "stats": dict(sorted(stats.items())),
        "findings": [f.to_dict() for f in findings],
        "new": [f.to_dict() for f in new + grammar],
        "grandfathered": [f.to_dict() for f in grandfathered],
        "stale_baseline": stale,
        "suppressed": [f.to_dict() for f in suppressed],
        "unused_suppressions": unused,
        "ok": not new and not grammar and not stale,
    }
