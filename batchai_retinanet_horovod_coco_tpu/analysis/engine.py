"""Invariant lint engine: shared machinery for project-wide AST passes.

The scaling, observability, and serving layers all rest on invariants that
were previously enforced only by convention plus two bespoke one-off audit
scripts (``scripts/audit_collectives.py``, ``scripts/audit_threads.py``):
bounded queues with shed reasons, crash-re-raise error contracts on every
long-lived thread, ONE monotonic clock, pure jit bodies, and collectives
that every host reaches unconditionally.  This module is the engine those
invariants are encoded against (the rules live in ``analysis/rules/``):

- **Rule registry** — rules self-register via the ``@register`` decorator;
  each rule is a pure function of one parsed file (``FileContext``) and
  returns ``Finding``s.  Rules are lexical AST passes: no imports of the
  linted code, no jax, stdlib only (this package must stay importable in
  jax-free processes, e.g. shm decode workers' CI checks).
- **Project rules** (ISSUE 20) — rules registered via ``@register_project``
  receive a ``ProjectContext`` (every parsed ``FileContext`` plus a
  package-local import map and one-level call/attribute resolution) and may
  reason ACROSS files: the lock-order deadlock detector, the
  lock-held-blocking pass, and the event-vocabulary contract checker.
  Cross-file findings carry a ``paths`` set and fingerprint on the SORTED
  path set, so line/file drift in one member never churns the baseline key.
- **Parse-once cache + ``--jobs N``** — files are parsed into a process-wide
  cache keyed on (path, mtime, size); repeated runs (tier-1 runs the engine
  several times) skip re-parsing, and the per-file phase fans out over a
  thread pool.  Report output is byte-identical to the serial run: results
  are re-assembled in the deterministic file-iteration order.
- **Uniform suppression grammar** — ``# lint: <rule>[,<rule>...]: <why>``
  on the offending line or the line directly above it.  The rationale is
  REQUIRED non-empty: a suppression without a why, or naming an unknown
  rule, is itself a finding (rule name ``suppression``).  Suppressions are
  tracked, and unused ones are reported (informationally) in the JSON
  report so dead exemptions can be garbage-collected.
- **Committed baseline** — ``analysis/baseline.json`` grandfathers known
  findings by line-number-insensitive fingerprint ``(rule, path, snippet)``
  so new violations fail while old ones stay tracked.  The baseline is
  NON-GROWING by construction: a run fails on new findings AND on stale
  baseline entries (a fixed finding must be removed from the baseline via
  ``--update-baseline``, so the file only ever shrinks).
- **JSON report** — ``--json`` emits one machine-readable object (findings,
  new/grandfathered/stale split, suppressions used and unused, per-rule
  site counters proving the pass actually inspected constructs).

Run:
    python -m batchai_retinanet_horovod_coco_tpu.analysis            # lint
    python -m batchai_retinanet_horovod_coco_tpu.analysis --json
    python -m batchai_retinanet_horovod_coco_tpu.analysis --update-baseline

Wired into ``make lint`` / ``make check-static`` and tier-1
(tests/unit/test_lint.py::test_tree_is_clean).
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import threading
import tokenize
from collections import Counter
from typing import Callable, Iterable

#: Reserved rule name for engine-level suppression-grammar findings.
SUPPRESSION_RULE = "suppression"

_SUPPRESS_RE = re.compile(
    r"#\s*lint:\s*(?P<rules>[A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)\s*"
    r":(?P<why>.*)$"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one site.

    ``path``/``line`` anchor the finding (and its suppression comment) at
    ONE site; a cross-file finding additionally carries ``paths`` — every
    file involved — and fingerprints on the sorted path SET, so line drift
    in one member file never churns a multi-file baseline entry."""

    rule: str
    path: str  # repo-relative (stable across checkouts; baseline key part)
    line: int  # 1-based; NOT part of the baseline key (lines drift)
    message: str
    snippet: str = ""  # stripped source line; the line-insensitive key part
    paths: tuple[str, ...] = ()  # cross-file findings: the full path set

    def __post_init__(self):
        if not isinstance(self.paths, tuple):  # baseline round-trips lists
            object.__setattr__(self, "paths", tuple(self.paths))

    def path_key(self) -> str:
        """The baseline path component: the sorted ``;``-joined path set
        for cross-file findings, the single path otherwise."""
        return ";".join(sorted(self.paths)) if self.paths else self.path

    def key(self) -> tuple[str, str, str]:
        return (self.rule, self.path_key(), self.snippet)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class Suppression:
    """A parsed ``# lint: <rules>: <why>`` comment."""

    line: int  # line the comment sits on
    applies_to: int  # code line it covers (own line, or first code line below)
    rules: tuple[str, ...]
    why: str
    used: bool = False


class FileContext:
    """Everything a rule may look at for one file: source, lines, AST,
    parsed suppressions, and where the file sits (package vs. script —
    some sub-checks only bind inside the package)."""

    def __init__(self, path: str, relpath: str, source: str,
                 in_package: bool = True):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.in_package = in_package
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.suppressions = _parse_suppressions(source, self.lines)
        self.stats: Counter = Counter()  # per-rule inspected-site counters

    # -- helpers rules share -------------------------------------------

    def reset(self) -> None:
        """Clear per-run mutable state (stats, suppression ``used`` flags)
        so a cached parse can be reused by the next run."""
        self.stats = Counter()
        for sup in self.suppressions:
            sup.used = False

    def snippet(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def finding(self, rule: str, line: int, message: str) -> Finding:
        return Finding(rule=rule, path=self.relpath, line=line,
                       message=message, snippet=self.snippet(line))

    def count(self, rule: str, n: int = 1) -> None:
        """Record that ``rule`` actually inspected ``n`` constructs here —
        the non-vacuity evidence the clean-tree test asserts on."""
        self.stats[rule] += n


def _parse_suppressions(source: str, lines: list[str]) -> list[Suppression]:
    """Tokenize-based comment scan (regex over raw lines would misfire on
    ``# lint:`` text inside string literals)."""
    comments: list[tuple[int, int, str]] = []  # (line, col, text)
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                comments.append((tok.start[0], tok.start[1], tok.string))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return []
    out = []
    for lineno, col, text in comments:
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        rules = tuple(r.strip() for r in m.group("rules").split(","))
        why = m.group("why").strip()
        # Own line if the comment trails code; otherwise the first
        # non-blank, non-comment line below it.
        own = lines[lineno - 1][:col].strip() if lineno <= len(lines) else ""
        applies_to = lineno
        if not own:
            j = lineno  # 0-based index of the next line
            while j < len(lines):
                stripped = lines[j].strip()
                if stripped and not stripped.startswith("#"):
                    applies_to = j + 1
                    break
                j += 1
        out.append(Suppression(line=lineno, applies_to=applies_to,
                               rules=rules, why=why))
    return out


# ---- rule registry -------------------------------------------------------

#: name -> (description, check(ctx) -> list[Finding])
RULES: dict[str, tuple[str, Callable[[FileContext], list[Finding]]]] = {}

#: name -> (description, check(project) -> list[Finding]) — whole-program
#: passes that see every parsed file at once (ISSUE 20).
PROJECT_RULES: dict[
    str, tuple[str, Callable[["ProjectContext"], list[Finding]]]
] = {}


def register(name: str, description: str):
    """Decorator: publish a per-file rule under ``name`` in the registry."""

    def deco(fn: Callable[[FileContext], list[Finding]]):
        if name == SUPPRESSION_RULE or name in PROJECT_RULES:
            raise ValueError(f"rule name {name!r} is reserved or taken")
        RULES[name] = (description, fn)
        return fn

    return deco


def register_project(name: str, description: str):
    """Decorator: publish a whole-program rule under ``name``."""

    def deco(fn: Callable[["ProjectContext"], list[Finding]]):
        if name == SUPPRESSION_RULE or name in RULES:
            raise ValueError(f"rule name {name!r} is reserved or taken")
        PROJECT_RULES[name] = (description, fn)
        return fn

    return deco


def all_rule_names() -> list[str]:
    return sorted(set(RULES) | set(PROJECT_RULES))


def _ensure_rules_loaded() -> None:
    # Import for the registration side effect; cheap and idempotent.
    from batchai_retinanet_horovod_coco_tpu.analysis import rules  # noqa: F401


# ---- project context -----------------------------------------------------


class ProjectContext:
    """Everything a project rule may look at: every parsed ``FileContext``,
    a package-local import map, and one-level attribute/call resolution
    helpers.  Rules share expensive intermediates (the lock graph) through
    ``cache`` and surface machine-readable artifacts (the computed lock
    order) through ``exports``, which ``run()`` folds into the report."""

    def __init__(self, contexts: list[FileContext], root: str,
                 lock_order_path: str | None = None):
        self.root = root
        self.contexts = list(contexts)
        self.by_path: dict[str, FileContext] = {
            c.relpath: c for c in self.contexts
        }
        self.lock_order_path = lock_order_path
        self.stats: Counter = Counter()
        self.cache: dict[str, object] = {}
        self.exports: dict[str, object] = {}

    def count(self, rule: str, n: int = 1) -> None:
        self.stats[rule] += n

    # -- package-local module naming / imports -------------------------

    def module_name(self, ctx: FileContext) -> str | None:
        """Dotted module path relative to the package root for in-package
        files (``serve/fleet.py`` → ``serve.fleet``), None for scripts."""
        if not ctx.in_package:
            return None
        rel = ctx.relpath.replace(os.sep, "/")
        prefix = PACKAGE_NAME + "/"
        if rel.startswith(prefix):
            rel = rel[len(prefix):]
        if rel.endswith("/__init__.py"):
            rel = rel[: -len("/__init__.py")]
        elif rel.endswith(".py"):
            rel = rel[:-3]
        return rel.replace("/", ".")

    def context_for_module(self, dotted: str) -> FileContext | None:
        """The FileContext behind a package-relative dotted module name."""
        index = self.cache.get("_module_index")
        if index is None:
            index = {}
            for c in self.contexts:
                mod = self.module_name(c)
                if mod is not None:
                    index[mod] = c
            self.cache["_module_index"] = index
        return index.get(dotted)

    def import_map(self, ctx: FileContext) -> dict[str, str]:
        """Local name → package-relative dotted target for this file's
        package-local imports: ``from ...serve import fleet`` → {'fleet':
        'serve.fleet'}; ``from ...obs.trace import monotonic_s`` →
        {'monotonic_s': 'obs.trace.monotonic_s'}.  Absolute package paths
        only (the tree imports by absolute name throughout)."""
        key = ("_imports", ctx.relpath)
        cached = self.cache.get(key)
        if cached is not None:
            return cached  # type: ignore[return-value]
        out: dict[str, str] = {}
        prefix = PACKAGE_NAME + "."
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name.startswith(prefix):
                        local = a.asname or a.name.split(".")[-1]
                        out[local] = a.name[len(prefix):]
            elif isinstance(node, ast.ImportFrom):
                if not node.module or not node.module.startswith(
                    PACKAGE_NAME
                ):
                    continue
                base = node.module[len(PACKAGE_NAME):].lstrip(".")
                for a in node.names:
                    target = f"{base}.{a.name}" if base else a.name
                    out[a.asname or a.name] = target
        self.cache[key] = out
        return out


# ---- per-file run --------------------------------------------------------

@dataclasses.dataclass
class FileResult:
    findings: list[Finding]
    suppressed: list[Finding]
    grammar_findings: list[Finding]  # bad suppression comments
    unused_suppressions: list[Suppression]
    stats: Counter


def _validate_rule_names(rule_names: Iterable[str] | None) -> list[str]:
    """Resolve a rule selection to known names; raise on typos (a typo'd
    ``--rule`` must not die with a raw KeyError deep in the walk)."""
    if rule_names is None:
        return all_rule_names()
    names = list(rule_names)
    unknown = [
        n for n in names if n not in RULES and n not in PROJECT_RULES
    ]
    if unknown:
        raise ValueError(
            f"unknown rule(s) {unknown} (known: {all_rule_names()})"
        )
    return names


def _validate_suppressions(
    ctx: FileContext,
) -> tuple[list[Finding], list[Suppression]]:
    """Split parsed suppressions into grammar findings + valid comments."""
    grammar: list[Finding] = []
    valid: list[Suppression] = []
    for sup in ctx.suppressions:
        bad = False
        if not sup.why:
            grammar.append(ctx.finding(
                SUPPRESSION_RULE, sup.line,
                "suppression missing rationale: '# lint: <rule>: <why>' "
                "requires a non-empty why",
            ))
            bad = True
        unknown = [
            r for r in sup.rules
            if r not in RULES and r not in PROJECT_RULES
        ]
        if unknown:
            grammar.append(ctx.finding(
                SUPPRESSION_RULE, sup.line,
                f"suppression names unknown rule(s) {unknown} "
                f"(known: {all_rule_names()})",
            ))
            bad = True
        if not bad:
            valid.append(sup)
    return grammar, valid


def _lint_context(
    ctx: FileContext, names: list[str],
) -> tuple[list[Finding], list[Finding], list[Finding], list[Suppression]]:
    """Per-file rules over one parsed context.  Returns (kept, suppressed,
    grammar, valid_suppressions); ``unused`` is NOT computed here — project
    rules may still consume a suppression later in the run."""
    grammar, valid = _validate_suppressions(ctx)
    raw: list[Finding] = []
    for name in names:
        if name not in RULES:  # project rules run later, on ProjectContext
            continue
        _desc, fn = RULES[name]
        raw.extend(fn(ctx))
    kept: list[Finding] = []
    suppressed: list[Finding] = []
    for f in raw:
        sup = _match_suppression(valid, f)
        if sup is not None:
            sup.used = True
            suppressed.append(f)
        else:
            kept.append(f)
    return kept, suppressed, grammar, valid


def lint_source(path: str, relpath: str, source: str, *,
                rule_names: Iterable[str] | None = None,
                in_package: bool = True) -> FileResult:
    """Run the (selected) per-file rules over one file's source."""
    _ensure_rules_loaded()
    names = _validate_rule_names(rule_names)
    try:
        ctx = FileContext(path, relpath, source, in_package=in_package)
    except SyntaxError as e:
        f = Finding(rule=SUPPRESSION_RULE, path=relpath, line=e.lineno or 0,
                    message=f"unparseable file: {e.msg}", snippet="")
        return FileResult([f], [], [], [], Counter())

    kept, suppressed, grammar, valid = _lint_context(ctx, names)
    unused = [s for s in valid if not s.used]
    return FileResult(kept, suppressed, grammar, unused, ctx.stats)


def _match_suppression(sups: list[Suppression], f: Finding):
    for sup in sups:
        if f.rule in sup.rules and f.line in (sup.line, sup.applies_to):
            return sup
    return None


# ---- tree walk -----------------------------------------------------------

PACKAGE_NAME = "batchai_retinanet_horovod_coco_tpu"

#: Directories under scripts/ that are NOT linted: xla_repros holds
#: filing-ready standalone upstream repro scripts whose text is frozen.
_SCRIPT_EXCLUDES = {"xla_repros", "__pycache__"}


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def iter_target_files(root: str | None = None):
    """Yield ``(abspath, relpath, in_package)`` for every linted file: the
    whole package tree, top-level driver scripts, and scripts/ (tests and
    the frozen xla repro scripts excluded)."""
    root = root or repo_root()
    pkg = os.path.join(root, PACKAGE_NAME)
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                p = os.path.join(dirpath, fn)
                yield p, os.path.relpath(p, root), True
    for fn in sorted(os.listdir(root)):
        if fn.endswith(".py"):
            yield os.path.join(root, fn), fn, False
    scripts = os.path.join(root, "scripts")
    if os.path.isdir(scripts):
        for dirpath, dirnames, filenames in os.walk(scripts):
            dirnames[:] = sorted(
                d for d in dirnames if d not in _SCRIPT_EXCLUDES
            )
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    p = os.path.join(dirpath, fn)
                    yield p, os.path.relpath(p, root), False


# ---- baseline ------------------------------------------------------------

def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


def load_baseline(path: str) -> Counter:
    """Multiset of grandfathered finding keys."""
    if not os.path.exists(path):
        return Counter()
    with open(path) as f:
        data = json.load(f)
    return Counter(
        (e["rule"], e["path"], e["snippet"]) for e in data.get("entries", [])
    )


def write_baseline(path: str, findings: list[Finding]) -> None:
    from batchai_retinanet_horovod_coco_tpu.utils.atomicio import (
        atomic_write_text,
    )

    entries = sorted(
        ({"rule": f.rule, "path": f.path_key(), "snippet": f.snippet}
         for f in findings),
        key=lambda e: (e["path"], e["rule"], e["snippet"]),
    )
    atomic_write_text(
        path,
        json.dumps({"version": 1, "entries": entries}, indent=1,
                   sort_keys=True) + "\n",
    )


# ---- parse cache ---------------------------------------------------------

#: (abspath, mtime_ns, size) -> FileContext.  Parsing dominates wall time
#: and tier-1 runs the engine several times in one process; a hit skips
#: re-parsing (``ctx.reset()`` clears per-run mutable state).  Entries for
#: a path are replaced on any stat change, so the cache never serves a
#: stale tree.
_CONTEXT_CACHE: dict[tuple[str, int, int], FileContext] = {}
_CACHE_LOCK = threading.Lock()


def _get_context(path: str, relpath: str, in_package: bool) -> FileContext:
    """Parse ``path`` (or reuse the cached parse).  Raises SyntaxError."""
    st = os.stat(path)
    key = (os.path.abspath(path), st.st_mtime_ns, st.st_size)
    with _CACHE_LOCK:
        ctx = _CONTEXT_CACHE.get(key)
    if ctx is not None and ctx.relpath == relpath:
        ctx.reset()
        return ctx
    with open(path) as f:
        source = f.read()
    ctx = FileContext(path, relpath, source, in_package=in_package)
    with _CACHE_LOCK:
        # Drop any older snapshot of the same path before inserting.
        for k in [k for k in _CONTEXT_CACHE
                  if k[0] == key[0] and k != key]:
            del _CONTEXT_CACHE[k]
        _CONTEXT_CACHE[key] = ctx
    return ctx


# ---- whole-run driver ----------------------------------------------------

def default_lock_order_path(root: str | None = None) -> str:
    """The committed static lock order lives next to ``baseline.json`` —
    resolved relative to the scanned root so fixture trees get their own
    (usually absent) file instead of the live one."""
    if root is None:
        return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "lock_order.json")
    return os.path.join(root, PACKAGE_NAME, "analysis", "lock_order.json")


def run(root: str | None = None, *, baseline_path: str | None = None,
        rule_names: Iterable[str] | None = None, jobs: int = 1,
        lock_order_path: str | None = None) -> dict:
    """Lint the tree, split findings against the baseline, return the
    report object (the ``--json`` payload).

    Phases: parse every file (``jobs`` wide; results assembled in the
    deterministic iteration order, so the report is byte-identical to a
    serial run), run per-file rules, then build one ``ProjectContext`` and
    run the whole-program rules, then match suppressions and split against
    the baseline."""
    _ensure_rules_loaded()
    names = _validate_rule_names(rule_names)
    root = root or repo_root()
    baseline_path = baseline_path or default_baseline_path()
    lock_order_path = lock_order_path or default_lock_order_path(root)
    baseline = load_baseline(baseline_path)

    targets = list(iter_target_files(root))
    files_scanned = len(targets)

    def _one(target):
        path, relpath, in_pkg = target
        try:
            ctx = _get_context(path, relpath, in_pkg)
        except SyntaxError as e:
            f = Finding(rule=SUPPRESSION_RULE, path=relpath,
                        line=e.lineno or 0,
                        message=f"unparseable file: {e.msg}", snippet="")
            return None, ([f], [], [], [])
        return ctx, _lint_context(ctx, names)

    if jobs > 1 and len(targets) > 1:
        from concurrent.futures import ThreadPoolExecutor

        # watchdog: bounded-lifetime CLI pool — `with` joins every worker
        # before run() returns; nothing long-lived to heartbeat.
        with ThreadPoolExecutor(max_workers=jobs) as pool:
            results = list(pool.map(_one, targets))
    else:
        results = [_one(t) for t in targets]

    findings: list[Finding] = []
    suppressed: list[Finding] = []
    grammar: list[Finding] = []
    stats: Counter = Counter()
    contexts: list[FileContext] = []
    valid_by_path: dict[str, list[Suppression]] = {}
    for ctx, (kept, supd, gram, valid) in results:
        findings.extend(kept)
        suppressed.extend(supd)
        grammar.extend(gram)
        if ctx is not None:
            stats.update(ctx.stats)
            contexts.append(ctx)
            valid_by_path[ctx.relpath] = valid

    # Whole-program rules: one ProjectContext over every parsed file,
    # run serially (they share cached intermediates).  A project finding
    # anchors at one (path, line) and honours that file's suppressions.
    project_names = [n for n in names if n in PROJECT_RULES]
    pctx = ProjectContext(contexts, root, lock_order_path=lock_order_path)
    for name in project_names:
        _desc, fn = PROJECT_RULES[name]
        for f in fn(pctx):
            sup = _match_suppression(valid_by_path.get(f.path, []), f)
            if sup is not None:
                sup.used = True
                suppressed.append(f)
            else:
                findings.append(f)
    stats.update(pctx.stats)

    unused: list[dict] = []
    for ctx in contexts:
        unused.extend(
            {"path": ctx.relpath, "line": s.line, "rules": list(s.rules),
             "why": s.why}
            for s in valid_by_path.get(ctx.relpath, []) if not s.used
        )

    # Bad suppression comments are never baselinable: they fail outright.
    remaining = Counter(baseline)
    new: list[Finding] = []
    grandfathered: list[Finding] = []
    for f in findings:
        if remaining[f.key()] > 0:
            remaining[f.key()] -= 1
            grandfathered.append(f)
        else:
            new.append(f)
    stale = [
        {"rule": r, "path": p, "snippet": s, "missing": n}
        for (r, p, s), n in sorted(remaining.items()) if n > 0
    ]
    return {
        "root": root,
        "rules": sorted(names),
        "files_scanned": files_scanned,
        "stats": dict(sorted(stats.items())),
        "findings": [f.to_dict() for f in findings],
        "new": [f.to_dict() for f in new + grammar],
        "grandfathered": [f.to_dict() for f in grandfathered],
        "stale_baseline": stale,
        "suppressed": [f.to_dict() for f in suppressed],
        "unused_suppressions": unused,
        "exports": pctx.exports,
        "ok": not new and not grammar and not stale,
    }
