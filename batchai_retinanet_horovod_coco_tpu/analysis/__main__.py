"""CLI for the invariant lint engine (``make lint``).

Exit codes: 0 clean (new findings = 0, stale baseline entries = 0),
1 otherwise.  ``--update-baseline`` rewrites the committed baseline from
the current findings — the sanctioned way to SHRINK it after fixing a
grandfathered violation (adding new entries is a review-visible diff).
"""

from __future__ import annotations

import argparse
import json
import sys

from batchai_retinanet_horovod_coco_tpu.analysis import engine


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m batchai_retinanet_horovod_coco_tpu.analysis",
        description=__doc__.splitlines()[0],
    )
    ap.add_argument("root", nargs="?", default=None,
                    help="repo root to lint (default: this checkout)")
    ap.add_argument("--json", action="store_true",
                    help="print the full machine-readable report")
    ap.add_argument("--rule", action="append", default=None,
                    help="run only this rule (repeatable)")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: analysis/baseline.json)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from current findings")
    ap.add_argument("--jobs", type=int, default=1, metavar="N",
                    help="parse/lint files with an N-wide thread pool "
                         "(report is byte-identical to serial)")
    ap.add_argument("--update-lock-order", action="store_true",
                    help="rewrite analysis/lock_order.json from the "
                         "computed may-hold-while-acquiring edges")
    args = ap.parse_args(argv)

    if (args.update_baseline or args.update_lock_order) and args.rule:
        # A single-rule run sees only that rule's findings; rewriting the
        # baseline from it would silently drop every OTHER rule's
        # grandfathered entries and fail the next full run.
        print("lint: --update-baseline/--update-lock-order require a "
              "full run (drop --rule)", file=sys.stderr)
        return 2

    try:
        report = engine.run(args.root, baseline_path=args.baseline,
                            rule_names=args.rule, jobs=max(1, args.jobs))
    except ValueError as e:
        print(f"lint: {e}", file=sys.stderr)
        return 2
    if args.update_lock_order:
        from batchai_retinanet_horovod_coco_tpu.analysis.rules import (
            lock_graph,
        )

        path = engine.default_lock_order_path(args.root)
        edges = report["exports"].get("lock_order_edges", [])
        lock_graph.write_lock_order(path, edges)
        print(f"lint: lock order rewritten with {len(edges)} edge(s) "
              f"-> {path}")
        return 0
    if args.update_baseline:
        path = args.baseline or engine.default_baseline_path()
        engine.write_baseline(path, [
            engine.Finding(**f) for f in report["findings"]
        ])
        print(f"lint: baseline rewritten with "
              f"{len(report['findings'])} entr(y/ies) -> {path}")
        return 0
    if args.json:
        print(json.dumps(report))
        return 0 if report["ok"] else 1

    for f in report["new"]:
        print(f"{f['path']}:{f['line']}: [{f['rule']}] {f['message']}")
    for e in report["stale_baseline"]:
        print(f"STALE baseline entry ({e['rule']}, {e['path']}): "
              f"{e['snippet']!r} no longer found — run --update-baseline "
              "to shrink the baseline")
    n_new, n_stale = len(report["new"]), len(report["stale_baseline"])
    n_gf, n_sup = len(report["grandfathered"]), len(report["suppressed"])
    print(
        f"lint: {report['files_scanned']} files, "
        f"{len(report['rules'])} rules, sites inspected "
        f"{sum(report['stats'].values())} — "
        f"{n_new} new, {n_gf} grandfathered, {n_sup} suppressed, "
        f"{n_stale} stale baseline"
    )
    if report["unused_suppressions"]:
        print(f"note: {len(report['unused_suppressions'])} unused "
              "suppression(s) (see --json) — consider removing them")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
