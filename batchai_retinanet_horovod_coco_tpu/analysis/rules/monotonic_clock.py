"""monotonic-clock: one clock for durations — ``obs.trace.monotonic_s``.

The obs subsystem standardized every timestamp (spans, JSONL events,
watchdog heartbeats, serve deadlines) on ONE clock, ``obs.trace
.monotonic_s()``, so any two timestamps in a run are mutually comparable
and immune to wall-clock steps (NTP slew mid-run once made a "negative
latency" p50).  ``time.time()`` is therefore banned everywhere for
duration/latency math; where wall time is genuinely meant (run headers,
the trace exporter's wall anchor) suppress with
``# lint: monotonic-clock: <why>``.

Inside the package the rule goes further: raw ``time.monotonic()`` /
``time.perf_counter()`` are also flagged — they are monotonic, but they are
a SECOND clock; timestamps taken with them cannot be compared against span
or heartbeat times.  Top-level bench/driver scripts may keep raw
``perf_counter`` (standalone measurement harnesses that never mix their
timestamps into the obs stream).
"""

from __future__ import annotations

import ast

from batchai_retinanet_horovod_coco_tpu.analysis.engine import (
    FileContext,
    Finding,
    register,
)
from batchai_retinanet_horovod_coco_tpu.analysis.rules.common import (
    dotted,
    from_imports,
)

NAME = "monotonic-clock"

_PKG_ONLY = frozenset({"monotonic", "perf_counter", "monotonic_ns",
                       "perf_counter_ns"})


@register(NAME, "time.time() banned for durations; package times with "
                "obs.trace.monotonic_s")
def check(ctx: FileContext) -> list[Finding]:
    # `from time import time` style aliases of the banned callables.
    aliased = {
        local: orig
        for local, orig in from_imports(ctx.tree, "time").items()
        if orig == "time" or (ctx.in_package and orig in _PKG_ONLY)
    }
    out: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        name = None
        if isinstance(f, ast.Attribute) and dotted(f) is not None:
            path = dotted(f)
            if path == "time.time":
                name = "time.time"
            elif (ctx.in_package and path is not None
                  and path.startswith("time.")
                  and f.attr in _PKG_ONLY):
                name = path
        elif isinstance(f, ast.Name) and f.id in aliased:
            name = f"time.{aliased[f.id]}"
        if name is None:
            continue
        ctx.count(NAME)
        if name == "time.time":
            msg = (
                "time.time() is wall clock — for durations/latency use "
                "obs.trace.monotonic_s(); if wall time is genuinely meant, "
                "suppress with '# lint: monotonic-clock: <why>'"
            )
        else:
            msg = (
                f"{name}() is a second clock — package code times with "
                "obs.trace.monotonic_s() (THE clock) so timestamps are "
                "comparable across spans/events/heartbeats"
            )
        out.append(ctx.finding(NAME, node.lineno, msg))
    return out
