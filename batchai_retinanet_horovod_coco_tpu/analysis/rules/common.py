"""Small AST helpers shared by the lint rules (stdlib-only, jax-free)."""

from __future__ import annotations

import ast


def callee_name(call: ast.Call) -> str | None:
    """Terminal name of the callee: ``Thread`` for both ``Thread(...)``
    and ``threading.Thread(...)``."""
    fn = call.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def dotted(node: ast.expr) -> str | None:
    """``a.b.c`` for an Attribute/Name chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def def_map(tree: ast.AST) -> dict[str, ast.AST]:
    """Every function/lambda-less def in the module by BARE name (methods
    included — ``self._producer`` resolves via ``_producer``).  Last def
    wins on (rare) collisions; rules that resolve through this map are
    best-effort lexical passes, not a type checker."""
    out: dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out[node.name] = node
    return out


def resolve_callable(expr: ast.expr, defs: dict[str, ast.AST],
                     depth: int = 3) -> ast.AST | None:
    """Best-effort: the function body behind an expression passed as a
    callable (``target=self._run``, ``functools.partial(fn, x)``,
    ``lambda: fn(x)``).  Returns a FunctionDef/Lambda node or None."""
    if depth <= 0:
        return None
    if isinstance(expr, ast.Lambda):
        # A lambda that just adapts arguments: chase the called function.
        if isinstance(expr.body, ast.Call):
            inner = resolve_callable(expr.body.func, defs, depth - 1)
            if inner is not None:
                return inner
        return expr
    if isinstance(expr, ast.Name):
        return defs.get(expr.id)
    if isinstance(expr, ast.Attribute):
        return defs.get(expr.attr)
    if isinstance(expr, ast.Call) and callee_name(expr) == "partial":
        if expr.args:
            return resolve_callable(expr.args[0], defs, depth - 1)
    return None


def module_aliases(tree: ast.AST, module: str) -> set[str]:
    """Local names bound to ``module`` by imports: ``import numpy as np``
    -> {'np'}, ``import numpy`` -> {'numpy'}."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == module:
                    out.add(a.asname or a.name)
    return out


def from_imports(tree: ast.AST, module: str) -> dict[str, str]:
    """``from <module> import x as y`` -> {'y': 'x'}."""
    out: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == module:
            for a in node.names:
                out[a.asname or a.name] = a.name
    return out
