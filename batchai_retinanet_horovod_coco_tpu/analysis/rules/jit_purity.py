"""jit-purity: no host effects inside functions handed to jit/shard_map.

A traced function runs ONCE at compile time; host-side effects inside it
(``time.time``, ``print``, ``np.random``, ``.item()``, file I/O) either bake
a compile-time constant into the executable (the classic "my timestamp never
changes" bug), force a silent device→host sync, or simply never execute
again after tracing.  The AOT paths (``.lower().compile()``) make this
worse: the traced value is frozen into a serialized executable.

This rule finds the functions passed to ``jax.jit`` / ``shard_map`` (as
call arguments, decorators, or ``functools.partial(jax.jit, ...)``
decorators), resolves them lexically within the file (named defs, methods,
lambdas), and flags host-effect calls anywhere in the resolved body
(nested defs included).  ``jax.debug.print``/``jax.debug.callback`` are
sanctioned in-jit effects and are not flagged; so are the arguments of
``jax.pure_callback`` / ``jax.experimental.io_callback`` calls — those are
THE supported host-escape hatches, so their callback subtrees are exempt
(ISSUE 20).  A ``functools.lru_cache`` / ``functools.cache`` decorator on
a jit-handed function is flagged too: the cache keys on tracer OBJECTS, so
every trace misses and the cache retains tracers — a silent leak.
Cross-module callees are out of scope (lexical pass).  Suppress with
``# lint: jit-purity: <why>`` on the offending line (e.g. an intentional
trace-time log).
"""

from __future__ import annotations

import ast

from batchai_retinanet_horovod_coco_tpu.analysis.engine import (
    FileContext,
    Finding,
    register,
)
from batchai_retinanet_horovod_coco_tpu.analysis.rules.common import (
    callee_name,
    def_map,
    dotted,
    module_aliases,
    resolve_callable,
)

NAME = "jit-purity"

_JIT_NAMES = frozenset({"jit", "shard_map", "pmap"})
_BANNED_BUILTINS = frozenset({"print", "input", "breakpoint", "open"})
_BANNED_TIME = frozenset({"time", "monotonic", "perf_counter", "sleep",
                          "time_ns", "monotonic_ns"})
_HOST_SYNC_METHODS = frozenset({"item"})
#: The sanctioned host-escape hatches: host effects inside the callback
#: handed to these run OUTSIDE the trace, by design.
_CALLBACK_NAMES = frozenset({"pure_callback", "io_callback"})
#: Tracer-keyed memoization on a traced function: silent leak.
_CACHE_DECORATORS = frozenset({"lru_cache", "cache"})


def _is_callback_call(node: ast.Call) -> bool:
    """``jax.pure_callback(...)`` / ``jax.experimental.io_callback(...)``
    (bare from-imported names accepted too)."""
    return callee_name(node) in _CALLBACK_NAMES


def _is_jit_ref(expr: ast.expr) -> bool:
    """``jit`` / ``jax.jit`` / ``shard_map`` / ``jax.experimental...``."""
    if isinstance(expr, ast.Name):
        return expr.id in _JIT_NAMES
    if isinstance(expr, ast.Attribute):
        return expr.attr in _JIT_NAMES
    return False


def _jit_entry_targets(tree: ast.AST):
    """Yield (site_lineno, target_expr_or_fndef) for every jit entry."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_jit_ref(node.func):
            if node.args:
                yield node.lineno, node.args[0]
            else:
                # jit(static_argnames=...) factory: the target arrives via
                # a decorator or a later call — those sites handle it.
                for kw in node.keywords:
                    if kw.arg in ("f", "fun", "func"):
                        yield node.lineno, kw.value
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _is_jit_ref(dec):
                    yield node.lineno, node
                elif isinstance(dec, ast.Call):
                    if _is_jit_ref(dec.func):
                        # @jit(...) / @shard_map(mesh=...) factory form.
                        if not dec.args:
                            yield node.lineno, node
                    elif callee_name(dec) == "partial" and any(
                        _is_jit_ref(a) for a in dec.args[:1]
                    ):
                        # @functools.partial(jax.jit, static_argnames=...)
                        yield node.lineno, node


def _walk_sanctioned(fn: ast.AST):
    """``ast.walk`` that skips the subtrees of sanctioned host-escape
    calls (``jax.pure_callback`` / ``io_callback``): the callback and its
    arguments are host-side by contract."""
    stack = [fn]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.Call) and _is_callback_call(child):
                continue
            stack.append(child)


def _banned_calls(fn: ast.AST, np_aliases: set[str],
                  random_aliases: set[str]):
    """Yield (lineno, description) for host-effect calls in the body."""
    for node in _walk_sanctioned(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Name) and f.id in _BANNED_BUILTINS:
            yield node.lineno, f"{f.id}()"
            continue
        if not isinstance(f, ast.Attribute):
            continue
        path = dotted(f)
        if path is None:
            # Method on a computed value: only the host-sync set applies.
            if f.attr in _HOST_SYNC_METHODS and not node.args:
                yield node.lineno, f".{f.attr}()"
            continue
        parts = path.split(".")
        root = parts[0]
        if root == "time" and f.attr in _BANNED_TIME:
            yield node.lineno, f"{path}()"
        elif root in np_aliases and len(parts) >= 2 and parts[1] == "random":
            yield node.lineno, f"{path}() (host RNG traces to a constant)"
        elif root in random_aliases and len(parts) == 2:
            yield node.lineno, f"{path}() (host RNG traces to a constant)"
        elif f.attr in _HOST_SYNC_METHODS and not node.args and root != "jax":
            yield node.lineno, f".{f.attr}() (forces device->host sync)"


@register(NAME, "functions passed to jit/shard_map must be host-effect-free")
def check(ctx: FileContext) -> list[Finding]:
    defs = def_map(ctx.tree)
    np_aliases = module_aliases(ctx.tree, "numpy")
    random_aliases = module_aliases(ctx.tree, "random")
    out: list[Finding] = []
    seen: set[tuple[int, int]] = set()
    for site_line, target in _jit_entry_targets(ctx.tree):
        fn = (target if isinstance(
            target, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda))
            else resolve_callable(target, defs))
        if fn is None:
            continue  # cross-module callee — lexically out of scope
        ctx.count(NAME)
        fname = getattr(fn, "name", "<lambda>")
        for dec in getattr(fn, "decorator_list", []):
            dec_ref = dec.func if isinstance(dec, ast.Call) else dec
            dec_name = (dec_ref.attr if isinstance(dec_ref, ast.Attribute)
                        else dec_ref.id if isinstance(dec_ref, ast.Name)
                        else None)
            if dec_name in _CACHE_DECORATORS and \
                    (dec.lineno, 0) not in seen:
                seen.add((dec.lineno, 0))
                out.append(ctx.finding(
                    NAME, dec.lineno,
                    f"functools.{dec_name} on jit-compiled '{fname}' "
                    f"(jit entry at line {site_line}) — the cache keys on "
                    "tracer objects, so it never hits and retains tracers "
                    "(silent leak); memoize outside the traced function",
                ))
        for lineno, desc in _banned_calls(fn, np_aliases, random_aliases):
            if (id(fn), lineno) in seen:
                continue
            seen.add((id(fn), lineno))
            out.append(ctx.finding(
                NAME, lineno,
                f"host effect {desc} inside jit-compiled '{fname}' "
                f"(jit entry at line {site_line}) — traced once at compile "
                "time, not per step; hoist it out or use jax.debug.*",
            ))
    return out
