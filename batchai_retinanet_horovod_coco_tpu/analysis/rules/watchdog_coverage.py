"""watchdog-coverage: every spawn site registers with the stall watchdog.

The engine-resident port of ``scripts/audit_threads.py`` (which survives as
a thin shim over this rule): the stall watchdog (obs/watchdog.py) only
diagnoses components that heartbeat, so a ``threading.Thread`` /
``mp.Process`` / executor spawned without registering is a future "it hung
and nothing says why".  Every spawn call must have, within ``WINDOW`` lines:

- a ``watchdog.register(`` call (registration at the spawn site), or
- a legacy ``# watchdog:`` / ``# watchdog-exempt:`` rationale comment
  (grandfathered grammar, kept so the PR-3/PR-4 era markers stay valid), or
- a uniform ``# lint: watchdog-coverage: <why>`` suppression on the spawn
  line or the line above (the engine applies those after this rule runs).
"""

from __future__ import annotations

import ast
import re

from batchai_retinanet_horovod_coco_tpu.analysis.engine import (
    FileContext,
    Finding,
    register,
)
from batchai_retinanet_horovod_coco_tpu.analysis.rules.common import (
    callee_name,
)

NAME = "watchdog-coverage"

#: Constructors whose call sites spawn (or pool) concurrent execution.
SPAWN_NAMES = frozenset(
    {"Thread", "Timer", "Process", "ThreadPoolExecutor", "ProcessPoolExecutor"}
)

#: Lines around the spawn call searched for a registration or a rationale.
WINDOW = 8

MARKER_RE = re.compile(
    r"#\s*watchdog(?:-exempt)?\s*(?:\((?P<scope>[^)]*)\))?:\s*(?P<why>\S.*)"
)
REGISTER_RE = re.compile(r"\bwatchdog\.register\(")


def spawn_calls(tree: ast.AST):
    """Yield (lineno, callee_name) for every spawn-constructor call."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            name = callee_name(node)
            if name in SPAWN_NAMES:
                yield node.lineno, name


def violation_message(callee: str) -> str:
    return (
        f"{callee}() spawn without watchdog.register( or a "
        f"'# watchdog: <why>' rationale within {WINDOW} lines"
    )


@register(NAME, "spawn sites must register with the obs stall watchdog")
def check(ctx: FileContext) -> list[Finding]:
    out: list[Finding] = []
    for lineno, callee in spawn_calls(ctx.tree):
        ctx.count(NAME)
        lo = max(0, lineno - 1 - WINDOW)
        hi = min(len(ctx.lines), lineno + WINDOW)
        window = "\n".join(ctx.lines[lo:hi])
        if REGISTER_RE.search(window) or MARKER_RE.search(window):
            continue
        out.append(ctx.finding(NAME, lineno, violation_message(callee)))
    return out
