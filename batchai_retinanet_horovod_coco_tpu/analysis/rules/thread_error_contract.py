"""thread-error-contract: thread bodies must forward crashes, never eat them.

The shm-pipeline contract (PR 1, inherited by every long-lived thread since):
an exception in a background thread must re-raise in the driver — delivered
through the output queue, stored and re-raised at join, or otherwise pushed
to a crash channel.  A thread whose run loop lets exceptions escape dies
silently (CPython prints to stderr and the program wedges on a queue that
will never fill), and a broad ``except: pass`` is the same bug spelled
differently.

For every ``Thread(target=...)``/``Timer(..., fn)`` whose target resolves to
a function defined in the same file, this rule requires:

- at least one broad handler (``except:``, ``except Exception``,
  ``except BaseException``) somewhere in the target's body that does MORE
  than ``pass`` (i.e. plausibly forwards/records the crash), and
- no broad handler anywhere in the target whose body is only ``pass``
  (narrow handlers like ``except queue.Empty: pass`` are the normal poll
  idiom and stay legal).

Targets that cannot be resolved lexically (imported callables, bound
methods of other modules) are skipped — this is an AST pass, not a type
checker.  Suppression anchors follow the finding: the no-forwarding
finding anchors at the SPAWN site (put ``# lint: thread-error-contract:
<why>`` there when a thread is genuinely fire-and-forget); the
broad-except-swallows finding anchors at the offending ``except`` line
(put the comment on, or directly above, that handler).
"""

from __future__ import annotations

import ast

from batchai_retinanet_horovod_coco_tpu.analysis.engine import (
    FileContext,
    Finding,
    register,
)
from batchai_retinanet_horovod_coco_tpu.analysis.rules.common import (
    callee_name,
    def_map,
    resolve_callable,
)

NAME = "thread-error-contract"

_SPAWNERS = frozenset({"Thread", "Timer"})
_BROAD = frozenset({"Exception", "BaseException"})


def _target_expr(call: ast.Call) -> ast.expr | None:
    for kw in call.keywords:
        if kw.arg in ("target", "function"):
            return kw.value
    name = callee_name(call)
    if name == "Timer" and len(call.args) >= 2:
        return call.args[1]
    return None


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = []
    if isinstance(t, ast.Tuple):
        names = [getattr(e, "attr", getattr(e, "id", None)) for e in t.elts]
    else:
        names = [getattr(t, "attr", getattr(t, "id", None))]
    return any(n in _BROAD for n in names)


def _is_swallow(handler: ast.ExceptHandler) -> bool:
    """Body is only pass/.../docstring — the crash evaporates."""
    for stmt in handler.body:
        if isinstance(stmt, ast.Pass):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
            continue
        return False
    return True


@register(NAME, "thread targets must forward exceptions to a crash channel")
def check(ctx: FileContext) -> list[Finding]:
    defs = def_map(ctx.tree)
    out: list[Finding] = []
    seen_targets: set[int] = set()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if callee_name(node) not in _SPAWNERS:
            continue
        expr = _target_expr(node)
        if expr is None:
            continue
        fn = resolve_callable(expr, defs)
        if fn is None or isinstance(fn, ast.Lambda):
            continue  # lexically unresolvable — out of scope for this pass
        ctx.count(NAME)
        if id(fn) in seen_targets:
            continue  # one verdict per target function
        seen_targets.add(id(fn))
        broad_ok = False
        for sub in ast.walk(fn):
            if not isinstance(sub, ast.ExceptHandler):
                continue
            if not _is_broad(sub):
                continue
            if _is_swallow(sub):
                out.append(ctx.finding(
                    NAME, sub.lineno,
                    f"broad except in thread target '{fn.name}' swallows "
                    "the crash (body is only pass) — forward it to the "
                    "driver (queue/put, store-and-re-raise) instead",
                ))
            else:
                broad_ok = True
        if not broad_ok:
            out.append(ctx.finding(
                NAME, node.lineno,
                f"thread target '{fn.name}' has no broad except forwarding "
                "crashes to the driver — a failure here dies silently "
                "(shm-pipeline contract: crash must re-raise in the driver)",
            ))
    return out
