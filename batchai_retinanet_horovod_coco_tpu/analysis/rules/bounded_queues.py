"""bounded-queues: every queue construction must be bounded (or say why).

The serve layer exists to shed load instead of queueing it unboundedly
(503 + reason beats an OOM an hour later), and the data/eval pipelines use
bounded queues as their backpressure mechanism — an unbounded queue anywhere
in a producer/consumer chain silently converts a slow consumer into
unbounded host-memory growth.  This rule requires every
``queue.Queue``/``mp.Queue``-family construction to pass ``maxsize`` (as a
positional or keyword argument), or to carry a ``# lint: bounded-queues:
<why>`` rationale (e.g. "bounded by the slot-token protocol").

``SimpleQueue`` cannot be bounded at all, so it is always flagged: either
switch to ``Queue(maxsize=...)`` or justify the unboundedness.
"""

from __future__ import annotations

import ast

from batchai_retinanet_horovod_coco_tpu.analysis.engine import (
    FileContext,
    Finding,
    register,
)
from batchai_retinanet_horovod_coco_tpu.analysis.rules.common import (
    callee_name,
)

NAME = "bounded-queues"


def _literal_value(node: ast.expr):
    """Fold a (possibly sign-prefixed) numeric literal; None otherwise."""
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        inner = _literal_value(node.operand)
        return None if inner is None else -inner
    if isinstance(node, ast.Constant) and isinstance(node.value, (int, float)):
        return node.value
    return None

#: Constructors taking maxsize (queue.*, multiprocessing context queues,
#: asyncio.Queue all share the signature).
BOUNDABLE = frozenset({"Queue", "LifoQueue", "PriorityQueue", "JoinableQueue"})
#: Constructors with NO capacity knob at all.
UNBOUNDABLE = frozenset({"SimpleQueue"})


@register(NAME, "queue constructions must pass maxsize or carry a rationale")
def check(ctx: FileContext) -> list[Finding]:
    out: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = callee_name(node)
        if name in UNBOUNDABLE:
            ctx.count(NAME)
            out.append(ctx.finding(
                NAME, node.lineno,
                f"{name}() has no capacity bound — use Queue(maxsize=...) "
                "or justify with '# lint: bounded-queues: <why>'",
            ))
        elif name in BOUNDABLE:
            ctx.count(NAME)
            maxsize = node.args[0] if node.args else None
            for kw in node.keywords:
                if kw.arg == "maxsize":
                    maxsize = kw.value
            if maxsize is None:
                out.append(ctx.finding(
                    NAME, node.lineno,
                    f"{name}() constructed without maxsize — unbounded "
                    "queueing defeats backpressure/shedding; bound it or "
                    "justify with '# lint: bounded-queues: <why>'",
                ))
            else:
                value = _literal_value(maxsize)
                if value is not None and value <= 0:
                    # Stdlib semantics: maxsize <= 0 means INFINITE — an
                    # explicitly-spelled unbounded queue is still unbounded.
                    out.append(ctx.finding(
                        NAME, node.lineno,
                        f"{name}(maxsize={value}) is unbounded by "
                        "stdlib semantics (<= 0 means infinite) — use a "
                        "positive bound or justify with "
                        "'# lint: bounded-queues: <why>'",
                    ))
    return out
