"""Event-vocabulary contract checker (ISSUE 20).

The observability surface is stringly-typed: ``sink.event("repin", ...)``
on one side, ``if ev["event"] == "repin"`` in a report section or smoke
check on the other, and nothing ties the two names together — the PR 16
review round found a dashboard reading a name nothing wrote.
``obs/vocabulary.py`` is the contract: every structured event, trace
instant, and telemetry series name is declared there with its intended
consumers.  This rule parses that registry STATICALLY (no import of the
linted tree), collects every emit site across the whole tree, and flags:

- **emitted-but-unregistered** — an emit site whose name literal is not in
  the vocabulary (at the emit site);
- **consumed-but-never-emitted** — a registered name that a declared
  consumer file actually references but no emit site produces (the typo /
  dead-producer class; at the vocabulary entry);
- **registered-but-never-emitted** — a registered name with no emit sites
  and no consumer references: stale vocabulary (at the entry);
- a declared consumer path that is not a scanned file (at the entry).

Emit sites are calls whose attribute is ``event`` / ``instant`` /
``counter`` / ``gauge`` / ``histogram`` (or an ``emit``/``_emit_event``
helper) with a string-literal first argument.  Dynamic names
(``sink.event(name, ...)``) are invisible to the rule and should be
funnelled through a registered prefix helper or suppressed with rationale.
"""

from __future__ import annotations

import ast

from batchai_retinanet_horovod_coco_tpu.analysis.engine import (
    Finding,
    PACKAGE_NAME,
    ProjectContext,
    register_project,
)

RULE = "event-vocabulary"

VOCABULARY_RELPATH = f"{PACKAGE_NAME}/obs/vocabulary.py"

#: call-attribute → emit kind
_EMIT_ATTRS = {
    "event": "event",
    "instant": "instant",
    "counter": "series",
    "gauge": "series",
    "histogram": "series",
    "emit": "event",
    "_emit_event": "event",
    "emit_event": "event",
}

#: files whose string literals are never emit sites: the registry itself
#: and the analysis engine/rules (they talk ABOUT names).
_EXCLUDED_PREFIXES = (
    f"{PACKAGE_NAME}/obs/vocabulary.py",
    f"{PACKAGE_NAME}/analysis/",
)


def _parse_vocabulary(source: str, tree: ast.AST) -> dict[str, dict]:
    """Extract the VOCABULARY dict literal without importing the module."""
    out: dict[str, dict] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
        else:
            continue
        if not any(isinstance(t, ast.Name) and t.id == "VOCABULARY"
                   for t in targets):
            continue
        if not isinstance(node.value, ast.Dict):
            continue
        for k, v in zip(node.value.keys, node.value.values):
            if not (isinstance(k, ast.Constant)
                    and isinstance(k.value, str)):
                continue
            entry = {"line": k.lineno, "kinds": (), "consumers": ()}
            if isinstance(v, ast.Dict):
                for ek, ev in zip(v.keys, v.values):
                    if not (isinstance(ek, ast.Constant)
                            and ek.value in ("kinds", "consumers")):
                        continue
                    vals = []
                    if isinstance(ev, (ast.Tuple, ast.List)):
                        vals = [e.value for e in ev.elts
                                if isinstance(e, ast.Constant)
                                and isinstance(e.value, str)]
                    entry[ek.value] = tuple(vals)
            out[k.value] = entry
    return out


def _emit_sites(pctx: ProjectContext):
    """Every ``(name, kind, relpath, line)`` emit site in the tree."""
    for ctx in pctx.contexts:
        rel = ctx.relpath.replace("\\", "/")
        if any(rel.startswith(p) for p in _EXCLUDED_PREFIXES):
            continue
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if isinstance(node.func, ast.Attribute):
                attr = node.func.attr
            elif isinstance(node.func, ast.Name):
                attr = node.func.id
            else:
                continue
            kind = _EMIT_ATTRS.get(attr)
            if kind is None:
                continue
            if not (node.args and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            yield node.args[0].value, kind, ctx.relpath, node.lineno


@register_project(
    RULE,
    "every structured event / trace instant / telemetry series name must "
    "be declared in obs/vocabulary.py; orphan consumers and stale entries "
    "are flagged")
def check(pctx: ProjectContext) -> list[Finding]:
    vocab_ctx = pctx.by_path.get(VOCABULARY_RELPATH)
    if vocab_ctx is None:
        return []  # fixture trees without a vocabulary: nothing to check
    vocab = _parse_vocabulary(vocab_ctx.source, vocab_ctx.tree)

    emits: dict[str, list[tuple[str, str, int]]] = {}
    findings: list[Finding] = []
    n_sites = 0
    for name, kind, relpath, line in _emit_sites(pctx):
        n_sites += 1
        emits.setdefault(name, []).append((kind, relpath, line))
        if name not in vocab:
            ctx = pctx.by_path[relpath]
            findings.append(Finding(
                rule=RULE, path=relpath, line=line,
                message=f"emitted-but-unregistered {kind} name {name!r}: "
                        f"declare it in obs/vocabulary.py with its "
                        f"intended consumers",
                snippet=ctx.snippet(line)))
    pctx.count(RULE, n_sites)
    pctx.exports["event_names_emitted"] = sorted(emits)

    for name, entry in sorted(vocab.items()):
        consumed_in: list[str] = []
        for consumer in entry["consumers"]:
            cctx = pctx.by_path.get(consumer)
            if cctx is None:
                findings.append(Finding(
                    rule=RULE, path=VOCABULARY_RELPATH,
                    line=entry["line"],
                    message=f"vocabulary entry {name!r} declares consumer "
                            f"{consumer!r} which is not a scanned file",
                    snippet=vocab_ctx.snippet(entry["line"])))
                continue
            if _references(cctx.tree, name):
                consumed_in.append(consumer)
        if name in emits:
            continue
        if consumed_in:
            findings.append(Finding(
                rule=RULE, path=VOCABULARY_RELPATH, line=entry["line"],
                message=f"consumed-but-never-emitted: {name!r} is read by "
                        f"{', '.join(consumed_in)} but nothing in the "
                        f"tree emits it",
                snippet=vocab_ctx.snippet(entry["line"])))
        else:
            findings.append(Finding(
                rule=RULE, path=VOCABULARY_RELPATH, line=entry["line"],
                message=f"registered-but-never-emitted: {name!r} has no "
                        f"emit site and no consumer reference — stale "
                        f"vocabulary entry",
                snippet=vocab_ctx.snippet(entry["line"])))
    return findings


def _references(tree: ast.AST, name: str) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and node.value == name:
            return True
    return False
