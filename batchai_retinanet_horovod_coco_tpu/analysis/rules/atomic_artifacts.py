"""atomic-artifacts: package artifact writes must commit via rename.

The durability subsystem's restore path (utils/checkpoint.py) SCANS
directories and trusts what it finds; so do the export loader, the tune
schedule registry, the lint baseline, and the obs trace merger.  A plain
``open(path, "w")`` publishes the file name BEFORE the bytes: a reader
racing the write — or a process SIGKILLed mid-write, the exact fault
``scripts/chaos.py`` injects — observes a truncated artifact that either
crashes the consumer or silently loads as garbage.  The invariant: every
write-truncate ``open`` in the package commits through tmp-then-rename —
either the ``utils.atomicio`` helpers (``atomic_write_json`` & co.) or an
inline ``os.replace``/``os.rename`` in the same function.

Rule: an ``open(..., "w"/"wb"/...)`` call (any truncating/creating mode:
'w' or 'x'; append 'a' and read 'r' are exempt) inside the package is a
finding unless its nearest enclosing function (module scope for
top-level writes) also calls ``os.replace``/``os.rename`` or an
``atomic_write_*`` helper.  Genuinely append-only sinks and write-once
private temp files suppress with ``# lint: atomic-artifacts: <why>``.

Scope: package only (``ctx.in_package``) — top-level bench/driver
scripts own their artifacts' lifecycles and are audited by review, not
this lexical pass.
"""

from __future__ import annotations

import ast

from batchai_retinanet_horovod_coco_tpu.analysis.engine import (
    FileContext,
    Finding,
    register,
)
from batchai_retinanet_horovod_coco_tpu.analysis.rules.common import dotted

NAME = "atomic-artifacts"

_RENAMES = frozenset({"os.replace", "os.rename"})
_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef)


def _mode_literal(call: ast.Call) -> str | None:
    """The literal mode of an ``open`` call (positional or keyword);
    None when absent or not a string literal (dynamic modes are not
    inspectable — out of scope for a lexical pass)."""
    mode: ast.expr | None = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if mode is None:
        return None
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return mode.value
    return None


def _scope_nodes(scope: ast.AST):
    """All nodes of one function scope (module = the top scope), NOT
    descending into nested function definitions — the nearest enclosing
    function owns its writes."""
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        node = stack.pop()
        if isinstance(node, _FUNCS + (ast.Lambda,)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _nested_defs(scope: ast.AST):
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        node = stack.pop()
        if isinstance(node, _FUNCS):
            yield node
            continue
        stack.extend(ast.iter_child_nodes(node))


def _sanctions(fn: ast.AST) -> bool:
    """Does this function commit via rename (or the atomicio helpers)?
    Nested helpers count — defining ``_commit()`` with the replace inside
    and calling it is the same pattern, one indirection deeper."""
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        path = dotted(node.func)
        if path in _RENAMES:
            return True
        name = path.rsplit(".", 1)[-1] if path else None
        if name is not None and name.startswith("atomic_write"):
            return True
    return False


@register(NAME, "write-truncate open() in the package must commit via "
                "tmp-then-rename (utils.atomicio or os.replace)")
def check(ctx: FileContext) -> list[Finding]:
    if not ctx.in_package:
        return []
    out: list[Finding] = []

    def scan(scope: ast.AST) -> None:
        sanctioned: bool | None = None  # computed lazily, once per scope
        for node in _scope_nodes(scope):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "open"
            ):
                continue
            mode = _mode_literal(node)
            if mode is None or not any(c in mode for c in ("w", "x")):
                continue
            ctx.count(NAME)
            if sanctioned is None:
                sanctioned = _sanctions(scope)
            if sanctioned:
                continue
            out.append(
                ctx.finding(
                    NAME, node.lineno,
                    "write-truncate open() with no rename commit in this "
                    "function: a reader (or a kill mid-write) sees a torn "
                    "artifact — write via utils.atomicio.atomic_write_* "
                    "or tmp + os.replace; append-only sinks suppress "
                    "with '# lint: atomic-artifacts: <why>'",
                )
            )
        for fn in _nested_defs(scope):
            scan(fn)

    scan(ctx.tree)
    return out
