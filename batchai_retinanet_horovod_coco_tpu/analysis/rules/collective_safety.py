"""collective-safety: no collectives under rank-conditional branches.

The static sibling of the HLO-level ``scripts/audit_collectives.py``: a
``psum``/``pmean``/``all_gather``/... that only SOME hosts reach is a
silent multi-host deadlock — the participating hosts block in the
collective forever while the skipping host runs ahead (the classic
``if process_index() == 0: checkpoint(psum(...))`` bug; the comms schedule
is *the* scaling artifact, and it must be unconditional).

This rule flags any collective call that sits lexically inside an ``if`` /
``while`` / ternary whose test mentions a rank-ish identifier
(``process_index``, ``process_count``, ``rank``, ``local_rank``,
``host_id``).  Lexical means conservative: a collective in EITHER branch
of a rank-conditional is flagged (both-branches-collective is still a
different schedule per host).  Rank-conditional HOST-side work (logging,
checkpoint writes) is fine — only collective calls under the branch are
findings.  Suppress with ``# lint: collective-safety: <why>`` when every
host provably takes the same branch (e.g. the condition is
replica-identical by construction).
"""

from __future__ import annotations

import ast

from batchai_retinanet_horovod_coco_tpu.analysis.engine import (
    FileContext,
    Finding,
    register,
)
from batchai_retinanet_horovod_coco_tpu.analysis.rules.common import (
    callee_name,
)

NAME = "collective-safety"

COLLECTIVES = frozenset({
    "psum", "pmean", "pmax", "pmin", "psum_scatter", "all_gather",
    "all_to_all", "ppermute", "pshuffle", "axis_index_groups",
    # comm/ subsystem wrappers (ISSUE 13): each of these performs
    # psum_scatter/all_gather internally, so a rank-guarded CALL to the
    # wrapper is the same deadlock as a rank-guarded raw collective —
    # the rule must see through the abstraction.
    "reduce_tree", "zero_gather_updates", "bucketed_pmean",
    "reduce_leaves", "quantized_pmean", "comm_metrics",
    # Hierarchical tree (ISSUE 16): the per-bucket two-level reducer
    # runs four grouped collectives internally — same see-through rule.
    "reduce_bucket_hierarchical",
})
_RANKY = frozenset({
    "process_index", "process_count", "rank", "local_rank", "host_id",
})


def _ranky_names(test: ast.expr) -> list[str]:
    found = []
    for node in ast.walk(test):
        if isinstance(node, ast.Name) and node.id in _RANKY:
            found.append(node.id)
        elif isinstance(node, ast.Attribute) and node.attr in _RANKY:
            found.append(node.attr)
    return found


class _Visitor(ast.NodeVisitor):
    def __init__(self, ctx: FileContext):
        self.ctx = ctx
        self.findings: list[Finding] = []
        self._cond_stack: list[str] = []  # ranky names guarding this scope

    def _visit_conditional(self, node, test, bodies):
        ranky = _ranky_names(test)
        self.visit(test)
        if ranky:
            self._cond_stack.append(ranky[0])
        for child in bodies:
            self.visit(child)
        if ranky:
            self._cond_stack.pop()

    def visit_If(self, node: ast.If):
        self._visit_conditional(node, node.test, node.body + node.orelse)

    def visit_While(self, node: ast.While):
        self._visit_conditional(node, node.test, node.body + node.orelse)

    def visit_IfExp(self, node: ast.IfExp):
        self._visit_conditional(node, node.test, [node.body, node.orelse])

    def visit_Call(self, node: ast.Call):
        name = callee_name(node)
        if name in COLLECTIVES:
            self.ctx.count(NAME)
            if self._cond_stack:
                self.findings.append(self.ctx.finding(
                    NAME, node.lineno,
                    f"collective '{name}' under a rank-conditional branch "
                    f"(test mentions '{self._cond_stack[-1]}') — a host "
                    "that skips it deadlocks every host that doesn't",
                ))
        self.generic_visit(node)


@register(NAME, "every host must reach every collective unconditionally")
def check(ctx: FileContext) -> list[Finding]:
    v = _Visitor(ctx)
    v.visit(ctx.tree)
    return v.findings
