"""Whole-program concurrency analysis (ISSUE 20): the may-hold-while-
acquiring graph and the two project rules built on it.

``lock-order`` — every lock in the tree gets a stable dotted identity
(``serve.fleet.FleetRouter._lock`` for ``self._lock = threading.Lock()``
inside ``FleetRouter``; the literal name for ``utils.locks.make_lock("x")``
sites).  Every ``with <lock>:`` / ``.acquire()`` scope contributes edges
*held → acquired* for the locks taken inside it — including, one call level
deep, the locks taken by package-local callees invoked from inside the
scope.  Any cycle in that graph is a potential deadlock and is reported
with every acquisition chain named.  The acyclic edge set is committed as
``analysis/lock_order.json`` under the same non-growing discipline as
``baseline.json``: a computed edge missing from the committed file fails
(review-visible ``--update-lock-order`` to accept), and a committed edge
no longer computed fails as stale.  The committed order is also what the
``utils/locks.py`` runtime witness enforces under ``RETINANET_LOCK_DEBUG=1``.

``lock-held-blocking`` — flags blocking operations performed while any
lock is held: ``Queue.get/put`` with no timeout, zero-arg ``.join()`` /
``.wait()`` / ``.result()``, ``time.sleep``, socket operations, HTTP
fetches, and ``subprocess`` waits.  Each finding names the full hold-site →
(call chain) → blocking-site path.

Both rules are best-effort lexical passes with ONE level of call/attribute
resolution — they over-approximate may-hold (a suppression with rationale
is the escape hatch) and under-approximate aliasing (a lock smuggled
through an untyped parameter is invisible).
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os

from batchai_retinanet_horovod_coco_tpu.analysis.engine import (
    Finding,
    FileContext,
    ProjectContext,
    register_project,
)
from batchai_retinanet_horovod_coco_tpu.analysis.rules.common import (
    callee_name,
    dotted,
)

RULE_ORDER = "lock-order"
RULE_BLOCKING = "lock-held-blocking"

#: Constructors that create a lock-like object.  Condition shares the
#: identity of the lock it wraps when given one; a bare Condition() is its
#: own identity (it owns a private RLock).
_LOCK_CTORS = {"Lock", "RLock", "Condition"}
_MAKE_LOCK = {"make_lock", "make_rlock"}


# ---- data model ----------------------------------------------------------


@dataclasses.dataclass
class LockDef:
    """One lock object with a stable dotted identity."""

    identity: str
    relpath: str
    line: int
    kind: str  # "Lock" | "RLock" | "Condition" | "named"


@dataclasses.dataclass
class Acq:
    """A direct acquisition event inside one function."""

    identity: str
    line: int


@dataclasses.dataclass
class Blocking:
    """A direct potentially-blocking call inside one function."""

    desc: str
    line: int


@dataclasses.dataclass
class FuncInfo:
    """Per-function facts used for one-level call resolution."""

    qual: str  # "Class.method" or "func"
    module: str
    relpath: str
    node: ast.AST
    cls: str | None
    direct_acquires: list[Acq] = dataclasses.field(default_factory=list)
    direct_blocking: list[Blocking] = dataclasses.field(default_factory=list)


@dataclasses.dataclass(frozen=True)
class Edge:
    src: str  # held
    dst: str  # acquired while held


@dataclasses.dataclass
class Evidence:
    relpath: str
    line: int
    holder: str  # qualified function where src is held
    via: str  # "" for direct, "call <name>()" for one-level


class LockGraph:
    """The shared intermediate both rules (and ``--update-lock-order``)
    consume; built once per run and cached on ``ProjectContext``."""

    def __init__(self):
        self.locks: dict[str, LockDef] = {}
        # (module, cls-or-None, attr) -> identity
        self.table: dict[tuple[str, str | None, str], str] = {}
        # (module, cls, attr) -> dotted class name of the attribute value
        # (for one-level self.pool._lock resolution)
        self.attr_types: dict[tuple[str, str, str], str] = {}
        # (module, qual) -> FuncInfo
        self.funcs: dict[tuple[str, str], FuncInfo] = {}
        # classes per module (for resolving ClassName(...) construction)
        self.classes: dict[str, set[str]] = {}
        self.edges: dict[Edge, list[Evidence]] = {}
        self.blocking: list[tuple[str, Evidence, str]] = []
        #: acquisition sites actually resolved to an identity
        self.sites = 0
        #: calls inspected while >=1 lock held (blocking-rule coverage)
        self.calls_inspected = 0

    def add_edge(self, e: Edge, ev: Evidence) -> None:
        if e.src == e.dst:
            return  # RLock reentry / over-approximated aliasing
        self.edges.setdefault(e, []).append(ev)


def module_of(pctx: ProjectContext, ctx: FileContext) -> str:
    """Dotted module for in-package files; path-derived pseudo-module for
    scripts (``scripts/chaos.py`` → ``scripts.chaos``)."""
    mod = pctx.module_name(ctx)
    if mod is not None:
        return mod
    rel = ctx.relpath.replace(os.sep, "/")
    if rel.endswith(".py"):
        rel = rel[:-3]
    return rel.replace("/", ".")


# ---- pass 1: lock definitions, attribute types, function index ----------


def _lock_kind(call: ast.Call) -> str | None:
    name = callee_name(call)
    if name in _LOCK_CTORS:
        d = dotted(call.func)
        # Accept bare Lock() and threading.Lock(); reject foo.Lock() from
        # unrelated modules only when the base is clearly not threading.
        if d is None or d == name or d == f"threading.{name}":
            return name
    if name in _MAKE_LOCK:
        return "named"
    return None


def _named_identity(call: ast.Call) -> str | None:
    if call.args and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str):
        return call.args[0].value
    return None


def _scan_file(graph: LockGraph, pctx: ProjectContext,
               ctx: FileContext) -> None:
    mod = module_of(pctx, ctx)
    graph.classes.setdefault(mod, set())

    def record_lock(key: tuple[str, str | None, str], call: ast.Call,
                    default_identity: str) -> None:
        kind = _lock_kind(call)
        if kind is None:
            return
        if kind == "named":
            identity = _named_identity(call) or default_identity
        elif kind == "Condition" and call.args:
            # Condition(wrapping_lock): share the wrapped lock's identity
            # when it resolves, else own identity.
            inner = _resolve_lock_expr(
                graph, mod, key[1], call.args[0], local=None)
            identity = inner or default_identity
        else:
            identity = default_identity
        graph.table[key] = identity
        graph.locks.setdefault(identity, LockDef(
            identity=identity, relpath=ctx.relpath, line=call.lineno,
            kind=kind))

    def scan_assign(node: ast.stmt, cls: str | None) -> None:
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            return
        value = node.value
        if not isinstance(value, ast.Call):
            return
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        for t in targets:
            if isinstance(t, ast.Name):
                key = (mod, cls, t.id)
                record_lock(key, value, f"{mod}.{cls + '.' if cls else ''}"
                                        f"{t.id}")
            elif isinstance(t, ast.Attribute) and \
                    isinstance(t.value, ast.Name) and t.value.id == "self" \
                    and cls is not None:
                key = (mod, cls, t.attr)
                record_lock(key, value, f"{mod}.{cls}.{t.attr}")
                # Remember the constructed type of plain attributes for
                # one-level self.<attr>.<lock> resolution.
                if _lock_kind(value) is None:
                    ctor = dotted(value.func)
                    if ctor:
                        graph.attr_types[(mod, cls, t.attr)] = ctor

    def scan_body(body: list[ast.stmt], cls: str | None,
                  prefix: str) -> None:
        for node in body:
            if isinstance(node, ast.ClassDef):
                graph.classes[mod].add(node.name)
                scan_body(node.body, node.name, prefix)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{cls}.{node.name}" if cls else node.name
                graph.funcs[(mod, qual)] = FuncInfo(
                    qual=qual, module=mod, relpath=ctx.relpath,
                    node=node, cls=cls)
                for sub in ast.walk(node):
                    scan_assign(sub, cls)
            else:
                scan_assign(node, cls)
                # module-level `if` guards etc.
                for sub in ast.iter_child_nodes(node):
                    if isinstance(sub, ast.stmt):
                        scan_assign(sub, cls)

    scan_body(ctx.tree.body, None, mod)


# ---- lock-expression resolution ------------------------------------------


def _resolve_lock_expr(graph: LockGraph, mod: str, cls: str | None,
                       expr: ast.expr,
                       local: dict[str, str] | None,
                       imports: dict[str, str] | None = None) -> str | None:
    """Map the expression in ``with <expr>:`` / ``<expr>.acquire()`` to a
    lock identity, or None when it cannot be resolved."""
    if isinstance(expr, ast.Name):
        if local and expr.id in local:
            return local[expr.id]
        hit = graph.table.get((mod, cls, expr.id)) \
            or graph.table.get((mod, None, expr.id))
        if hit:
            return hit
        if imports:
            target = imports.get(expr.id)
            if target and "." in target:
                m, n = target.rsplit(".", 1)
                return graph.table.get((m, None, n))
        return None
    if isinstance(expr, ast.Attribute):
        base = expr.value
        if isinstance(base, ast.Name):
            if base.id in ("self", "cls") and cls is not None:
                return graph.table.get((mod, cls, expr.attr))
            # class-qualified: Frontend._stream_lock in the same module
            if base.id in graph.classes.get(mod, ()):
                return graph.table.get((mod, base.id, expr.attr))
            # module alias: fleet._LOCK after `from ..serve import fleet`
            if imports:
                target = imports.get(base.id)
                if target:
                    return graph.table.get((target, None, expr.attr)) \
                        or graph.table.get((target, base.id, expr.attr))
            return None
        if isinstance(base, ast.Attribute) and \
                isinstance(base.value, ast.Name) and \
                base.value.id == "self" and cls is not None:
            # ONE level: self.<attr>.<lock> through the attr's known type.
            ctor = graph.attr_types.get((mod, cls, base.attr))
            if ctor:
                owner_mod, owner_cls = _resolve_class(
                    graph, mod, ctor, imports)
                if owner_cls:
                    return graph.table.get(
                        (owner_mod, owner_cls, expr.attr))
    return None


def _resolve_class(graph: LockGraph, mod: str, ctor: str,
                   imports: dict[str, str] | None
                   ) -> tuple[str, str | None]:
    """``SlotPool`` / ``batcher.SlotPool`` → (defining module, class)."""
    if "." in ctor:
        head, cls = ctor.rsplit(".", 1)
        target = (imports or {}).get(head, head)
        if cls in graph.classes.get(target, ()):
            return target, cls
        return target, None
    if ctor in graph.classes.get(mod, ()):
        return mod, ctor
    target = (imports or {}).get(ctor)
    if target and "." in target:
        m, cls = target.rsplit(".", 1)
        if cls in graph.classes.get(m, ()):
            return m, cls
    return mod, None


# ---- pass 2: per-function direct acquisitions / blocking calls -----------


def _walk_pruned(node: ast.AST):
    """``ast.walk`` that does NOT descend into nested function/class/lambda
    bodies (their statements execute elsewhere)."""
    stack = list(ast.iter_child_nodes(node))
    yield node
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda, ast.ClassDef)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


_BLOCKING_DOTTED = ("time.sleep", "urllib.request.urlopen", "urlopen")
_BLOCKING_PREFIXES = ("subprocess.", "requests.", "socket.")
_SUBPROCESS_FNS = {"run", "call", "check_call", "check_output"}
_SOCKET_METHODS = {"recv", "recv_into", "accept", "connect", "sendall"}


def _has_kw(call: ast.Call, *names: str) -> bool:
    return any(kw.arg in names for kw in call.keywords)


def _blocking_desc(call: ast.Call, time_aliases: set[str]) -> str | None:
    """Classify one call as potentially blocking, or None."""
    d = dotted(call.func)
    name = callee_name(call)
    if d in _BLOCKING_DOTTED or (d and d.split(".", 1)[0] in time_aliases
                                 and name == "sleep"):
        return f"`{d}(...)`"
    if d:
        head = d.split(".", 1)[0] + "."
        if head in _BLOCKING_PREFIXES:
            if head == "subprocess." and name not in (
                    _SUBPROCESS_FNS | {"Popen"}):
                return None
            if _has_kw(call, "timeout"):
                return None
            return f"`{d}(...)`"
    if not isinstance(call.func, ast.Attribute):
        return None
    # Method-shape heuristics: zero-arg join/wait/result, no-timeout
    # queue get/put, socket methods, subprocess handle waits.
    if name == "join" and not call.args and not _has_kw(call, "timeout"):
        return "`.join()` with no timeout"
    if name == "result" and not call.args and not _has_kw(call, "timeout"):
        return "`.result()` with no timeout"
    if name == "communicate" and not _has_kw(call, "timeout"):
        return "`.communicate()` with no timeout"
    if name == "get" and not call.args and not _has_kw(
            call, "timeout", "block"):
        return "`.get()` with no timeout"
    if name == "put" and len(call.args) == 1 and not _has_kw(
            call, "timeout", "block"):
        return "`.put(...)` with no timeout"
    if name in _SOCKET_METHODS and not _has_kw(call, "timeout"):
        return f"`.{name}(...)` (socket)"
    return None


def _with_lock_items(graph: LockGraph, fi: FuncInfo, node: ast.With,
                     local: dict[str, str],
                     imports: dict[str, str]) -> list[tuple[str, int]]:
    out = []
    for item in node.items:
        ident = _resolve_lock_expr(graph, fi.module, fi.cls,
                                   item.context_expr, local, imports)
        if ident:
            out.append((ident, item.context_expr.lineno))
    return out


def _acquire_target(graph: LockGraph, fi: FuncInfo, call: ast.Call,
                    local: dict[str, str],
                    imports: dict[str, str]) -> str | None:
    if isinstance(call.func, ast.Attribute) and \
            call.func.attr in ("acquire", "release"):
        return _resolve_lock_expr(graph, fi.module, fi.cls,
                                  call.func.value, local, imports)
    return None


def _local_lock_defs(node: ast.stmt, mod: str, qual: str,
                     local: dict[str, str]) -> None:
    """Track function-local ``lk = threading.Lock()`` / ``make_lock(...)``
    bindings so later ``with lk:`` resolves."""
    if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
        kind = _lock_kind(node.value)
        if kind is not None:
            for t in node.targets:
                if isinstance(t, ast.Name):
                    ident = (_named_identity(node.value)
                             if kind == "named" else None)
                    local[t.id] = ident or f"{mod}.{qual}.{t.id}"


def _pass_direct(graph: LockGraph, pctx: ProjectContext) -> None:
    """Fill every FuncInfo's direct acquisitions and blocking calls."""
    for (mod, qual), fi in graph.funcs.items():
        ctx = pctx.by_path.get(fi.relpath)
        imports = pctx.import_map(ctx) if ctx is not None else {}
        time_aliases = {"time"}
        local: dict[str, str] = {}
        for node in _walk_pruned(fi.node):
            if node is fi.node:
                continue
            if isinstance(node, ast.stmt):
                _local_lock_defs(node, mod, qual, local)
            if isinstance(node, ast.With):
                for ident, line in _with_lock_items(
                        graph, fi, node, local, imports):
                    fi.direct_acquires.append(Acq(ident, line))
            elif isinstance(node, ast.Call):
                if isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "acquire":
                    ident = _acquire_target(graph, fi, node, local, imports)
                    if ident:
                        fi.direct_acquires.append(Acq(ident, node.lineno))
                desc = _blocking_desc(node, time_aliases)
                if desc:
                    fi.direct_blocking.append(Blocking(desc, node.lineno))


# ---- pass 3: held-scope walk → edges + blocking findings -----------------


def _resolve_call(graph: LockGraph, fi: FuncInfo, call: ast.Call,
                  imports: dict[str, str]) -> FuncInfo | None:
    """ONE level of package-local call resolution."""
    fn = call.func
    if isinstance(fn, ast.Name):
        target = graph.funcs.get((fi.module, fn.id))
        if target:
            return target
        imp = imports.get(fn.id)
        if imp and "." in imp:
            m, n = imp.rsplit(".", 1)
            return graph.funcs.get((m, n))
        return None
    if isinstance(fn, ast.Attribute):
        base = fn.value
        if isinstance(base, ast.Name):
            if base.id in ("self", "cls") and fi.cls is not None:
                return graph.funcs.get((fi.module,
                                        f"{fi.cls}.{fn.attr}"))
            imp = imports.get(base.id)
            if imp:
                return graph.funcs.get((imp, fn.attr))
        elif isinstance(base, ast.Attribute) and \
                isinstance(base.value, ast.Name) and \
                base.value.id == "self" and fi.cls is not None:
            ctor = graph.attr_types.get((fi.module, fi.cls, base.attr))
            if ctor:
                m, cls = _resolve_class(graph, fi.module, ctor, imports)
                if cls:
                    return graph.funcs.get((m, f"{cls}.{fn.attr}"))
    return None


def _pass_scopes(graph: LockGraph, pctx: ProjectContext) -> None:
    for (mod, qual), fi in graph.funcs.items():
        ctx = pctx.by_path.get(fi.relpath)
        imports = pctx.import_map(ctx) if ctx is not None else {}
        time_aliases = {"time"}
        local: dict[str, str] = {}

        def scan_expr(expr: ast.AST,
                      held: tuple[tuple[str, int], ...],
                      explicit: list[tuple[str, int]]) -> None:
            """Calls inside one expression (no nested statements here)."""
            for sub in _walk_pruned(expr):
                if not isinstance(sub, ast.Call):
                    continue
                all_held = held + tuple(explicit)
                if isinstance(sub.func, ast.Attribute) and \
                        sub.func.attr in ("acquire", "release"):
                    ident = _acquire_target(graph, fi, sub, local, imports)
                    if ident:
                        if sub.func.attr == "acquire":
                            graph.sites += 1
                            for src, _l in all_held:
                                graph.add_edge(
                                    Edge(src, ident),
                                    Evidence(fi.relpath, sub.lineno,
                                             f"{mod}.{qual}", ""))
                            explicit.append((ident, sub.lineno))
                        else:
                            for i in range(len(explicit) - 1, -1, -1):
                                if explicit[i][0] == ident:
                                    del explicit[i]
                                    break
                    continue
                if not all_held:
                    continue
                graph.calls_inspected += 1
                inner, inner_line = all_held[-1]
                hold = f"{inner} (acquired {fi.relpath}:{inner_line})"
                desc = _blocking_desc(sub, time_aliases)
                if desc is not None:
                    graph.blocking.append((desc, Evidence(
                        fi.relpath, sub.lineno, f"{mod}.{qual}", ""),
                        hold))
                    continue
                callee = _resolve_call(graph, fi, sub, imports)
                if callee is None or callee is fi:
                    continue
                for acq in callee.direct_acquires:
                    for src, _l in all_held:
                        graph.add_edge(Edge(src, acq.identity), Evidence(
                            fi.relpath, sub.lineno, f"{mod}.{qual}",
                            f"call {callee.module}.{callee.qual}() "
                            f"acquires at {callee.relpath}:{acq.line}"))
                for blk in callee.direct_blocking:
                    graph.blocking.append((blk.desc, Evidence(
                        fi.relpath, sub.lineno, f"{mod}.{qual}",
                        f"via {callee.module}.{callee.qual}() at "
                        f"{callee.relpath}:{blk.line}"), hold))

        def visit(stmts: list[ast.stmt],
                  held: tuple[tuple[str, int], ...],
                  explicit: list[tuple[str, int]]) -> None:
            # ``held`` = with-stack; ``explicit`` = live .acquire() holds.
            for node in stmts:
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.ClassDef)):
                    continue
                _local_lock_defs(node, mod, qual, local)
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    acquired = _with_lock_items(
                        graph, fi, node, local, imports)
                    graph.sites += len(acquired)
                    # ``with a, b:`` acquires sequentially — items earlier
                    # in the same statement are held when later ones are
                    # taken, so they contribute edges too.
                    running = list(held + tuple(explicit))
                    for ident, line in acquired:
                        for src, _src_line in running:
                            graph.add_edge(Edge(src, ident), Evidence(
                                fi.relpath, line, f"{mod}.{qual}", ""))
                        running.append((ident, line))
                    for item in node.items:
                        if not _resolve_lock_expr(
                                graph, fi.module, fi.cls,
                                item.context_expr, local, imports):
                            scan_expr(item.context_expr, held, explicit)
                    visit(node.body, held + tuple(acquired), explicit)
                    continue
                body_fields = [f for f in ("body", "orelse", "finalbody")
                               if getattr(node, f, None)]
                handlers = getattr(node, "handlers", [])
                if body_fields or handlers:
                    # Compound statement: scan header expressions, then
                    # recurse into nested statement lists (a `with` inside
                    # a loop must still open a scope).
                    for field in ("test", "iter", "subject"):
                        sub = getattr(node, field, None)
                        if sub is not None:
                            scan_expr(sub, held, explicit)
                    for field in body_fields:
                        visit(getattr(node, field), held, explicit)
                    for h in handlers:
                        visit(h.body, held, explicit)
                else:
                    scan_expr(node, held, explicit)

        visit(getattr(fi.node, "body", []), (), [])


# ---- graph construction entry point --------------------------------------


def build_graph(pctx: ProjectContext) -> LockGraph:
    cached = pctx.cache.get("lockgraph")
    if cached is not None:
        return cached  # type: ignore[return-value]
    graph = LockGraph()
    for ctx in pctx.contexts:
        _scan_file(graph, pctx, ctx)
    _pass_direct(graph, pctx)
    _pass_scopes(graph, pctx)
    pctx.cache["lockgraph"] = graph
    return graph


# ---- committed order -----------------------------------------------------


def load_lock_order(path: str) -> list[dict] | None:
    """The committed edge list, or None when the file does not exist
    (fixture trees get no drift check, only cycle detection)."""
    if not os.path.exists(path):
        return None
    with open(path) as f:
        data = json.load(f)
    return list(data.get("edges", []))


def write_lock_order(path: str, edges: list[dict]) -> None:
    from batchai_retinanet_horovod_coco_tpu.utils.atomicio import (
        atomic_write_text,
    )

    uniq = sorted(
        {(e["src"], e["dst"]) for e in edges}
    )
    atomic_write_text(path, json.dumps(
        {"version": 1,
         "edges": [{"src": s, "dst": d} for s, d in uniq]},
        indent=1, sort_keys=True) + "\n")


# ---- cycle detection -----------------------------------------------------


def _cycles(edges: dict[Edge, list[Evidence]]) -> list[list[str]]:
    """Every elementary cycle's node list (dedup by rotation), via DFS from
    each node over the identity digraph.  Graphs here are tiny (tens of
    nodes); Johnson's algorithm would be overkill."""
    adj: dict[str, set[str]] = {}
    for e in edges:
        adj.setdefault(e.src, set()).add(e.dst)
    seen: set[tuple[str, ...]] = set()
    out: list[list[str]] = []

    def dfs(start: str, node: str, path: list[str],
            on_path: set[str]) -> None:
        for nxt in sorted(adj.get(node, ())):
            if nxt == start:
                cyc = path[:]
                # canonicalize rotation so A->B->A == B->A->B
                i = cyc.index(min(cyc))
                canon = tuple(cyc[i:] + cyc[:i])
                if canon not in seen:
                    seen.add(canon)
                    out.append(list(canon))
            elif nxt not in on_path and nxt > start:
                # only expand nodes > start: each cycle found exactly once
                # from its smallest node
                path.append(nxt)
                on_path.add(nxt)
                dfs(start, nxt, path, on_path)
                on_path.discard(nxt)
                path.pop()

    for n in sorted(adj):
        dfs(n, n, [n], {n})
    return out


# ---- the rules -----------------------------------------------------------


@register_project(
    RULE_ORDER,
    "cross-module lock acquisition order: cycles in the may-hold-while-"
    "acquiring graph are potential deadlocks; the acyclic order is "
    "committed in analysis/lock_order.json (non-growing)")
def check_lock_order(pctx: ProjectContext) -> list[Finding]:
    graph = build_graph(pctx)
    pctx.count(RULE_ORDER, graph.sites)
    findings: list[Finding] = []

    edges_out = sorted({(e.src, e.dst) for e in graph.edges})
    pctx.exports["lock_order_edges"] = [
        {"src": s, "dst": d} for s, d in edges_out
    ]
    pctx.exports["lock_identities"] = sorted(graph.locks)

    for cyc in _cycles(graph.edges):
        chains = []
        files: set[str] = set()
        anchor: Evidence | None = None
        for i, src in enumerate(cyc):
            dst = cyc[(i + 1) % len(cyc)]
            evs = graph.edges.get(Edge(src, dst), [])
            ev = evs[0] if evs else None
            if ev is not None:
                files.add(ev.relpath)
                if anchor is None:
                    anchor = ev
                via = f"; {ev.via}" if ev.via else ""
                chains.append(f"{src} -> {dst} (held in {ev.holder}, "
                              f"acquired {ev.relpath}:{ev.line}{via})")
            else:
                chains.append(f"{src} -> {dst}")
        anchor = anchor or Evidence("", 0, "", "")
        ctx = pctx.by_path.get(anchor.relpath)
        findings.append(Finding(
            rule=RULE_ORDER, path=anchor.relpath, line=anchor.line,
            message="potential deadlock: lock acquisition cycle "
                    + " | ".join(chains),
            snippet=ctx.snippet(anchor.line) if ctx else "",
            paths=tuple(sorted(files)),
        ))

    committed = load_lock_order(pctx.lock_order_path) \
        if pctx.lock_order_path else None
    if committed is not None:
        want = {(e["src"], e["dst"]) for e in committed}
        have = set(edges_out)
        lock_rel = os.path.relpath(pctx.lock_order_path, pctx.root)
        for s, d in sorted(have - want):
            evs = graph.edges.get(Edge(s, d), [])
            ev = evs[0] if evs else Evidence("", 0, "", "")
            ctx = pctx.by_path.get(ev.relpath)
            via = f"; {ev.via}" if ev.via else ""
            findings.append(Finding(
                rule=RULE_ORDER, path=ev.relpath, line=ev.line,
                message=f"lock-order edge {s} -> {d} (held in {ev.holder}"
                        f"{via}) is not in the committed "
                        f"{lock_rel} — review and run --update-lock-order",
                snippet=ctx.snippet(ev.line) if ctx else "",
            ))
        for s, d in sorted(want - have):
            findings.append(Finding(
                rule=RULE_ORDER, path=lock_rel, line=0,
                message=f"stale committed lock-order edge {s} -> {d}: no "
                        f"longer computed from the tree — run "
                        f"--update-lock-order to shrink the order",
                snippet=f"{s} -> {d}",
            ))
    return findings


@register_project(
    RULE_BLOCKING,
    "blocking operations (no-timeout Queue get/put, join/wait/result, "
    "time.sleep, sockets/HTTP, subprocess waits) while a lock is held")
def check_lock_held_blocking(pctx: ProjectContext) -> list[Finding]:
    graph = build_graph(pctx)
    pctx.count(RULE_BLOCKING, graph.calls_inspected)
    findings = []
    for desc, ev, hold in graph.blocking:
        ctx = pctx.by_path.get(ev.relpath)
        via = f" {ev.via};" if ev.via else ""
        findings.append(Finding(
            rule=RULE_BLOCKING, path=ev.relpath, line=ev.line,
            message=f"blocking {desc} in {ev.holder} while holding "
                    f"{hold};{via} move the blocking call outside the "
                    f"critical section or add a timeout",
            snippet=ctx.snippet(ev.line) if ctx else "",
        ))
    return findings
