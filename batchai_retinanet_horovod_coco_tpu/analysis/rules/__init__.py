"""The lint rules. Importing this package registers every rule with the
engine registry (engine._ensure_rules_loaded does exactly that)."""

from batchai_retinanet_horovod_coco_tpu.analysis.rules import (  # noqa: F401
    atomic_artifacts,
    bounded_queues,
    collective_safety,
    event_vocabulary,
    jit_purity,
    lock_graph,
    monotonic_clock,
    thread_error_contract,
    watchdog_coverage,
)
