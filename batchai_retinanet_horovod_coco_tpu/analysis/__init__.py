"""Static-analysis subsystem: the invariant lint engine and its rules.

Stdlib-only and jax-free by design (the engine runs in CI shells and
pre-push hooks where paying a jax import would be absurd, and it lints
jax-free processes' code).  See ``engine.py`` for the architecture and
``rules/`` for the six encoded contracts:

- ``bounded-queues``        queue constructions must pass maxsize
- ``thread-error-contract`` thread bodies forward crashes to the driver
- ``jit-purity``            no host effects inside jit/shard_map bodies
- ``monotonic-clock``       one clock (obs.trace.monotonic_s) for durations
- ``collective-safety``     no collectives under rank-conditional branches
- ``watchdog-coverage``     every spawn site registers with the watchdog

Entry point: ``python -m batchai_retinanet_horovod_coco_tpu.analysis``
(``make lint``).
"""

from batchai_retinanet_horovod_coco_tpu.analysis.engine import (  # noqa: F401
    RULES,
    Finding,
    default_baseline_path,
    lint_source,
    load_baseline,
    run,
    write_baseline,
)
