"""CommConfig: the policy layer for how gradients cross the interconnect.

ISSUE 13 replaces the bare ``quantized_allreduce`` bool (one ``if`` in
train/step.py, per-leaf, no error feedback, unmeasured) with a first-class
policy object the whole stack resolves from:

- ``compress`` selects the wire format of the compressible collective
  phase: ``"none"`` (exact f32 — the compiled step is byte-identical to
  pre-ISSUE-13), ``"int8"`` (EQuARX-style symmetric per-block int8,
  ~5/8 the exact bytes-on-wire), or ``"bf16"`` (round-to-nearest bf16,
  ~3/4 the exact bytes);
- ``error_feedback`` carries the residual each step's quantization
  dropped in opt_state-adjacent comm state (``TrainState.comm_state``)
  and adds it back before the next quantize — the standard EF trick that
  turns biased rounding into an unbiased-in-expectation scheme (the
  telescoping sum: applied_1..T + residual_T == exact_1..T);
- ``overlap`` issues each schedule stage's compressed collective from
  INSIDE the backward pass (comm/overlap.py custom-vjp staging) so the
  interconnect works while later stages' gradients are still being
  computed; off, the whole tree reduces in one fused pass after the
  backward (identical math, fewer/larger collectives);
- ``bucket_mb`` packs many small leaves into one flattened bucket per
  schedule stage so they share ONE quantized collective (and one scale
  vector) instead of paying per-leaf collective latency + scale traffic;
- ``min_bucket_bytes`` subsumes the old ``parallel/quantize.py``
  ``_MIN_QUANTIZE_SIZE`` per-leaf blind spot: a bucket whose total
  payload is below this stays exact (the wire saving is noise there),
  but small leaves themselves are no longer skipped — they ride inside
  full-size buckets;
- ``stage_modes`` is the per-role policy override: e.g.
  ``(("heads", "bf16"),)`` keeps the (small, sensitive) head gradients
  at bf16 while the backbone runs int8;
- ``ici_mode`` / ``dcn_mode`` / ``dcn_bucket_mb`` (ISSUE 16) are the
  per-hop policy for the topology-aware hierarchical tree: a TPU pod is
  two fabrics — fast ICI within a slice, slow DCN across slices — and
  compression should pay only where bandwidth is scarce (EQuARX).  The
  hop fields are dormant until the step is handed a
  ``parallel.mesh.CommTopology``; then ``dcn_mode`` (default: inherit
  ``compress``) is the wire format of the cross-slice hop, ``ici_mode``
  (default ``"none"`` — the fast wire stays exact) that of the
  intra-slice hops, and ``dcn_bucket_mb`` sizes buckets for the hop
  that actually hurts.  Without a topology, ``compress`` applies to the
  whole flat tree exactly as before (ISSUE-13 behavior unchanged).

The object is a frozen dataclass so step factories can key compile
caches on it and workers can reconstruct it from CLI flags
deterministically.
"""

from __future__ import annotations

import dataclasses

#: Comm schedule stages, in backward-completion order: the heads' grads
#: exist first, the backbone's last — overlap issues each stage's
#: collective as soon as its cotangents exist.  Top-level param keys map
#: onto stages via ``stage_of``; anything that is not backbone/fpn
#: (cls_head, box_head, test models' ad-hoc keys) is "heads".
STAGES = ("backbone", "fpn", "heads")

COMPRESS_MODES = ("none", "int8", "bf16")


def stage_of(top_key: str) -> str:
    """Schedule stage of a top-level parameter key."""
    key = str(top_key)
    if key in ("backbone", "fpn"):
        return key
    return "heads"


@dataclasses.dataclass(frozen=True)
class CommConfig:
    """Policy for the gradient collectives (see module docstring)."""

    compress: str = "none"  # "none" | "int8" | "bf16"
    error_feedback: bool = True
    overlap: bool = False
    bucket_mb: float = 4.0
    # Buckets with payload below this stay exact (subsumes the old
    # per-leaf _MIN_QUANTIZE_SIZE = 8192 elements x 4 bytes).
    min_bucket_bytes: int = 32768
    block: int = 512  # elements per int8 scale (EQuARX-style blocks)
    # Per-role overrides: ((stage, mode), ...) — mode for unlisted
    # stages is ``compress`` (the DCN baseline when hierarchical).
    stage_modes: tuple = ()
    # Per-hop policy (ISSUE 16) — dormant until a CommTopology is
    # supplied.  None means "unset": ici defaults to "none" (the fast
    # wire stays exact), dcn inherits ``compress``, dcn_bucket_mb
    # inherits ``bucket_mb``.
    ici_mode: str | None = None
    dcn_mode: str | None = None
    dcn_bucket_mb: float | None = None

    def __post_init__(self):
        if self.compress not in COMPRESS_MODES:
            raise ValueError(
                f"CommConfig.compress must be one of {COMPRESS_MODES}, "
                f"got {self.compress!r}"
            )
        for stage, mode in self.stage_modes:
            if stage not in STAGES:
                raise ValueError(
                    f"CommConfig.stage_modes names unknown stage "
                    f"{stage!r}; valid stages are {STAGES}"
                )
            if mode not in COMPRESS_MODES:
                raise ValueError(
                    f"CommConfig.stage_modes[{stage!r}] must be one of "
                    f"{COMPRESS_MODES}, got {mode!r}"
                )
        if self.bucket_mb <= 0:
            raise ValueError(
                f"CommConfig.bucket_mb must be positive, "
                f"got {self.bucket_mb!r}"
            )
        if self.block <= 0:
            raise ValueError(
                f"CommConfig.block must be positive, got {self.block!r}"
            )
        for field in ("ici_mode", "dcn_mode"):
            value = getattr(self, field)
            if value is not None and value not in COMPRESS_MODES:
                raise ValueError(
                    f"CommConfig.{field} must be one of {COMPRESS_MODES} "
                    f"(or None to inherit), got {value!r}"
                )
        if self.dcn_bucket_mb is not None and self.dcn_bucket_mb <= 0:
            raise ValueError(
                f"CommConfig.dcn_bucket_mb must be positive (or None to "
                f"inherit bucket_mb), got {self.dcn_bucket_mb!r}"
            )
        ici, dcn = self.effective_ici_mode, self.effective_dcn_mode
        if ici != "none" and ici != dcn:
            raise ValueError(
                f"CommConfig.ici_mode: compressing the fast (ICI) hop "
                f"({ici!r}) while the DCN hop runs {dcn!r} is "
                "unsupported — the hierarchical tree compresses only "
                "the slow wire; set ici_mode='none' (exact) or give "
                "both hops one mode (which is the flat tree)"
            )

    @property
    def enabled(self) -> bool:
        """Any compression at all (overlap without compression still
        routes through the comm reduce, so it counts).  A hop-only
        policy (``compress='none'`` but ``dcn_mode`` set) counts too:
        it compresses the moment a multi-slice topology appears."""
        return (
            self.compress != "none"
            or self.overlap
            or self.effective_dcn_mode != "none"
        )

    @property
    def needs_state(self) -> bool:
        """Does this policy carry cross-step comm state (EF residuals)?"""
        return self.error_feedback and (
            self.compress != "none" or self.effective_dcn_mode != "none"
        )

    def mode_for_stage(self, stage: str, default: str | None = None) -> str:
        """Wire mode for a schedule stage.  ``default`` overrides the
        baseline (the hierarchical planner passes the hop's mode)."""
        baseline = self.compress if default is None else default
        return dict(self.stage_modes).get(stage, baseline)

    @property
    def effective_ici_mode(self) -> str:
        """Intra-slice wire mode once a topology engages ("none" unless
        explicitly set — the fast wire stays exact)."""
        return "none" if self.ici_mode is None else self.ici_mode

    @property
    def effective_dcn_mode(self) -> str:
        """Cross-slice wire mode once a topology engages (inherits
        ``compress`` unless explicitly set)."""
        return self.compress if self.dcn_mode is None else self.dcn_mode

    def hierarchical_with(self, topology) -> bool:
        """Does the hierarchical tree engage at ``topology``?  Requires
        a real multi-slice topology AND per-hop modes that differ —
        when both hops share one mode the hierarchy degenerates to the
        flat tree (and the step compiles the flat tree, byte-identical:
        the pinned contract)."""
        if topology is None or getattr(topology, "num_slices", 1) <= 1:
            return False
        return self.effective_ici_mode != self.effective_dcn_mode

    def flat_equivalent(self, topology) -> "CommConfig":
        """The flat-tree config this policy degenerates to when the
        hierarchical tree does NOT engage at ``topology``:

        - no topology → this config unchanged (legacy ISSUE-13 path);
        - single-slice topology → the whole world is the fast wire, so
          the flat tree runs at ``ici_mode`` (stage_modes are DCN-side
          overrides and a single slice has no DCN hop, so they drop);
        - multi-slice with ``ici_mode == dcn_mode`` → the flat tree at
          that shared mode (stage_modes keep their meaning).  Both hop
          fields are pinned to the shared mode — NOT cleared — so the
          result is a fixed point: re-resolving it against any topology
          never re-engages the hierarchy (``ici_mode=None`` would read
          back as "none" and differ from a non-"none" ``compress``).
        """
        if topology is None:
            return self
        if getattr(topology, "num_slices", 1) <= 1:
            return dataclasses.replace(
                self, compress=self.effective_ici_mode,
                ici_mode=None, dcn_mode=None, dcn_bucket_mb=None,
                stage_modes=(),
            )
        mode = self.effective_dcn_mode
        return dataclasses.replace(
            self, compress=mode, ici_mode=mode, dcn_mode=mode,
            dcn_bucket_mb=None,
        )

    @property
    def bucket_elems(self) -> int:
        """Bucket capacity in f32 elements."""
        return max(1, int(self.bucket_mb * (1 << 20) / 4))

    @property
    def dcn_bucket_elems(self) -> int:
        """Bucket capacity (f32 elements) for the hierarchical plan —
        sized for the hop that actually hurts (the DCN exchange);
        inherits ``bucket_mb`` unless ``dcn_bucket_mb`` is set."""
        mb = self.bucket_mb if self.dcn_bucket_mb is None else self.dcn_bucket_mb
        return max(1, int(mb * (1 << 20) / 4))
