"""CommConfig: the policy layer for how gradients cross the interconnect.

ISSUE 13 replaces the bare ``quantized_allreduce`` bool (one ``if`` in
train/step.py, per-leaf, no error feedback, unmeasured) with a first-class
policy object the whole stack resolves from:

- ``compress`` selects the wire format of the compressible collective
  phase: ``"none"`` (exact f32 — the compiled step is byte-identical to
  pre-ISSUE-13), ``"int8"`` (EQuARX-style symmetric per-block int8,
  ~5/8 the exact bytes-on-wire), or ``"bf16"`` (round-to-nearest bf16,
  ~3/4 the exact bytes);
- ``error_feedback`` carries the residual each step's quantization
  dropped in opt_state-adjacent comm state (``TrainState.comm_state``)
  and adds it back before the next quantize — the standard EF trick that
  turns biased rounding into an unbiased-in-expectation scheme (the
  telescoping sum: applied_1..T + residual_T == exact_1..T);
- ``overlap`` issues each schedule stage's compressed collective from
  INSIDE the backward pass (comm/overlap.py custom-vjp staging) so the
  interconnect works while later stages' gradients are still being
  computed; off, the whole tree reduces in one fused pass after the
  backward (identical math, fewer/larger collectives);
- ``bucket_mb`` packs many small leaves into one flattened bucket per
  schedule stage so they share ONE quantized collective (and one scale
  vector) instead of paying per-leaf collective latency + scale traffic;
- ``min_bucket_bytes`` subsumes the old ``parallel/quantize.py``
  ``_MIN_QUANTIZE_SIZE`` per-leaf blind spot: a bucket whose total
  payload is below this stays exact (the wire saving is noise there),
  but small leaves themselves are no longer skipped — they ride inside
  full-size buckets;
- ``stage_modes`` is the per-role policy override: e.g.
  ``(("heads", "bf16"),)`` keeps the (small, sensitive) head gradients
  at bf16 while the backbone runs int8.

The object is a frozen dataclass so step factories can key compile
caches on it and workers can reconstruct it from CLI flags
deterministically.
"""

from __future__ import annotations

import dataclasses

#: Comm schedule stages, in backward-completion order: the heads' grads
#: exist first, the backbone's last — overlap issues each stage's
#: collective as soon as its cotangents exist.  Top-level param keys map
#: onto stages via ``stage_of``; anything that is not backbone/fpn
#: (cls_head, box_head, test models' ad-hoc keys) is "heads".
STAGES = ("backbone", "fpn", "heads")

COMPRESS_MODES = ("none", "int8", "bf16")


def stage_of(top_key: str) -> str:
    """Schedule stage of a top-level parameter key."""
    key = str(top_key)
    if key in ("backbone", "fpn"):
        return key
    return "heads"


@dataclasses.dataclass(frozen=True)
class CommConfig:
    """Policy for the gradient collectives (see module docstring)."""

    compress: str = "none"  # "none" | "int8" | "bf16"
    error_feedback: bool = True
    overlap: bool = False
    bucket_mb: float = 4.0
    # Buckets with payload below this stay exact (subsumes the old
    # per-leaf _MIN_QUANTIZE_SIZE = 8192 elements x 4 bytes).
    min_bucket_bytes: int = 32768
    block: int = 512  # elements per int8 scale (EQuARX-style blocks)
    # Per-role overrides: ((stage, mode), ...) — mode for unlisted
    # stages is ``compress``.
    stage_modes: tuple = ()

    def __post_init__(self):
        if self.compress not in COMPRESS_MODES:
            raise ValueError(
                f"CommConfig.compress must be one of {COMPRESS_MODES}, "
                f"got {self.compress!r}"
            )
        for stage, mode in self.stage_modes:
            if mode not in COMPRESS_MODES:
                raise ValueError(
                    f"stage_modes[{stage!r}] must be one of "
                    f"{COMPRESS_MODES}, got {mode!r}"
                )
        if self.bucket_mb <= 0:
            raise ValueError("bucket_mb must be positive")
        if self.block <= 0:
            raise ValueError("block must be positive")

    @property
    def enabled(self) -> bool:
        """Any compression at all (overlap without compression still
        routes through the comm reduce, so it counts)."""
        return self.compress != "none" or self.overlap

    @property
    def needs_state(self) -> bool:
        """Does this policy carry cross-step comm state (EF residuals)?"""
        return self.error_feedback and self.compress != "none"

    def mode_for_stage(self, stage: str) -> str:
        return dict(self.stage_modes).get(stage, self.compress)

    @property
    def bucket_elems(self) -> int:
        """Bucket capacity in f32 elements."""
        return max(1, int(self.bucket_mb * (1 << 20) / 4))
