"""Bucketed, error-feedback gradient compression (the comm/ data plane).

The wire scheme per bucket is the EQuARX-style two-phase decomposition
``parallel/quantize.py`` proved out (reduce in exact f32, compress only
the phase that can take it), upgraded three ways (ISSUE 13):

1. **Bucketing** — leaves are packed per schedule stage into flat
   buckets of ``CommConfig.bucket_mb`` so many small leaves share ONE
   collective and one scale vector.  The old per-leaf
   ``_MIN_QUANTIZE_SIZE`` blind spot (biases/norm scales skipped
   per-leaf, paying exact bytes AND per-leaf collective latency) is
   subsumed: small leaves ride inside full buckets; only a bucket whose
   TOTAL payload is under ``min_bucket_bytes`` stays exact.
2. **Error feedback** — device ``i`` owns the reduced shard it
   quantizes, so it also owns the rounding error it introduced:
   ``residual = shard - dequant(quant(shard))`` is carried in
   ``TrainState.comm_state`` (a flat ``(n * chunk,)`` array per bucket,
   sharded over the data axis exactly like ZeRO optimizer state — same
   padding-is-zeros invariant, same ``reshard_flat_leaf`` elasticity)
   and added back before the next quantize.  The telescoping identity
   ``sum(applied) + residual_T == sum(exact)`` makes the scheme
   unbiased-in-expectation instead of one-step-biased.
3. **Health** — every reduce returns the local EF residual and the
   count of saturated (|q| == 127) elements, which the train step turns
   into the ``ef_residual_norm`` / ``ef_saturation`` /
   ``comm_compressed_bytes`` metrics (obs gauges + the always-armed
   ``ef_residual_spike`` SLO rule).

Two collective layouts share the per-bucket quantizer:

- ``reduce_tree`` — the DP path: per bucket, ``psum_scatter`` in f32
  (summation precision untouched), EF add-back, per-block int8/bf16
  quantize of the reduced shard, compressed ``all_gather``.  Every
  device dequantizes the same gathered bytes, so the update stays
  bitwise replicated.  Handed a ``parallel.mesh.CommTopology`` the
  tree becomes HIERARCHICAL (ISSUE 16): exact f32 reduce-scatter
  within each ICI slice, then the quantized exchange ONLY on the
  cross-slice DCN hop (reduce-scatter exact, gather compressed, EF
  residual keyed per hop — ``"<stage>.<index>@dcn"``), then an exact
  intra-slice all-gather.  Compression pays exactly where bandwidth is
  scarce; the ICI hops carry zero quantized bytes.  When both hops
  share one mode (or the topology is a single slice) the hierarchy
  degenerates and callers compile the FLAT tree — byte-identical HLO,
  pinned by tests.
- ``zero_gather_updates`` — the ZeRO path: the gradient reduce-scatter
  stays exact per-leaf (it feeds the sharded optimizer), and
  compression moves to the OTHER half of the traffic, the
  param-all-gather: each device quantizes its optimizer UPDATE shard
  (with per-leaf EF residuals in the ZeRO flat layout), gathers int8,
  and every device applies the identical dequantized update to its
  replicated params.  Gathering the *update* instead of the params is
  what lifts the old "quantizing the gather would bias the model"
  exclusivity: an update is a gradient-like increment, exactly what EF
  makes unbiased.

Non-finite gradients must SURFACE, not launder: a non-finite block
poisons its gathered scale to NaN (the ``parallel/quantize.py``
contract), so the loop's finite-check aborts exactly as on the exact
path.

House rules: everything here is jit-pure (pure jnp + named-axis
collectives, no clocks/IO); the collectives are unconditional — the
collective-safety lint rule knows these wrapper names (``reduce_tree``,
``zero_gather_updates``, ``bucketed_pmean``,
``reduce_bucket_hierarchical``) as collective call sites.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from batchai_retinanet_horovod_coco_tpu.comm.config import (
    CommConfig,
    stage_of,
)
from batchai_retinanet_horovod_coco_tpu.parallel.mesh import DATA_AXIS
from batchai_retinanet_horovod_coco_tpu.parallel.zero import _pad_flat


# ---------------------------------------------------------------------------
# The plan: a deterministic, n-independent bucketing of a gradient tree
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class BucketLeaf:
    path: str  # jax.tree_util.keystr of the full-tree path
    offset: int  # element offset within the bucket's logical flat
    size: int
    shape: tuple
    dtype: str


@dataclasses.dataclass(frozen=True)
class Bucket:
    stage: str
    index: int
    mode: str  # "exact" | "int8" | "bf16"
    leaves: tuple  # of BucketLeaf
    size: int  # total logical elements

    @property
    def key(self) -> str:
        return f"{self.stage}.{self.index}"


@dataclasses.dataclass(frozen=True)
class CommPlan:
    buckets: tuple  # of Bucket, stage-major in backward-completion order
    config: CommConfig

    def stage_buckets(self, stage: str) -> tuple:
        return tuple(b for b in self.buckets if b.stage == stage)

    @property
    def stages(self) -> tuple:
        seen = []
        for b in self.buckets:
            if b.stage not in seen:
                seen.append(b.stage)
        return tuple(seen)

    # ---- static wire accounting (per-device bytes sent, ring model) ----

    def _chunk(self, size: int, n: int) -> int:
        return -(-size // n)

    def _blocks(self, size: int, n: int) -> int:
        return -(-self._chunk(size, n) // self.config.block)

    def exact_bytes(self, n: int) -> int:
        """Per-device ring bytes of the uncompressed schedule: one f32
        all-reduce (reduce-scatter + all-gather) per bucket."""
        f = (n - 1) / max(n, 1)
        return int(sum(2 * f * 4 * b.size for b in self.buckets))

    def compressed_bytes(self, n: int) -> int:
        """Per-device ring bytes under this plan: exact f32
        reduce-scatter + compressed gather (int8 payload + one f32
        scale per block; bf16 payload; exact buckets unchanged)."""
        f = (n - 1) / max(n, 1)
        total = 0.0
        for b in self.buckets:
            rs = f * 4 * b.size
            if b.mode == "int8":
                gather = f * (b.size + 4 * n * self._blocks(b.size, n))
            elif b.mode == "bf16":
                gather = f * 2 * b.size
            else:
                gather = f * 4 * b.size
            total += rs + gather
        return int(total)

    def quant_elems(
        self, n: int, zero: bool = False, topology=None
    ) -> int:
        """Per-device INT8-quantized elements (the saturation
        denominator).  bf16 buckets are excluded — they can never
        saturate (no clip boundary), and counting them would dilute the
        gauge under mixed stage_modes.

        DP layout: one padded chunk per bucket.  ZeRO layout
        (``zero=True``): the quantized local vector is the concat of
        PER-LEAF padded chunks, which is larger whenever leaf sizes
        don't divide ``n`` — the denominator must match or the
        ``ef_saturation`` gauge over-reports on ZeRO runs.
        Hierarchical layout (``topology``): the quantized shard is the
        DCN-hop chunk (double-padded: first to the slice, then across
        slices)."""
        total = 0
        for b in self.buckets:
            if b.mode != "int8":
                continue
            if zero:
                total += sum(self._chunk(l.size, n) for l in b.leaves)
            elif topology is not None:
                total += self._hier_chunk(b.size, topology)
            else:
                total += self._chunk(b.size, n)
        return total

    # ---- per-hop accounting (the hierarchical tree, ISSUE 16) ----

    def _hier_chunk(self, size: int, topology) -> int:
        """Final per-device chunk of the hierarchical tree: the bucket
        pads to the slice count first (ICI tile), then that tile pads
        across slices (DCN tile)."""
        return self._chunk(
            self._chunk(size, topology.slice_size), topology.num_slices
        )

    def _hop_bucket_bytes(self, mode: str, size: int, topology) -> dict:
        """Per-device ring bytes of ONE bucket through the hierarchical
        tree, split by fabric.  The tree is: ICI reduce-scatter (f32),
        DCN reduce-scatter (f32) + gather (``mode``), ICI all-gather
        (f32).  ``mode == "exact"`` is also the model of a flat
        all-reduce routed hierarchically — the reference the DCN ratio
        is stated against."""
        S, L = topology.num_slices, topology.slice_size
        fi = (L - 1) / max(L, 1)
        fd = (S - 1) / max(S, 1)
        tile = size / max(L, 1)  # the per-slice ICI tile the DCN hop moves
        ici = fi * 4 * size * 2  # reduce-scatter + all-gather, both f32
        dcn_rs = fd * 4 * tile
        if mode == "int8":
            chunk = self._hier_chunk(size, topology)
            blocks = -(-chunk // self.config.block)
            dcn_gather = fd * (tile + 4 * S * blocks)
        elif mode == "bf16":
            dcn_gather = fd * 2 * tile
        else:
            dcn_gather = fd * 4 * tile
        return {"ici": ici, "dcn": dcn_rs + dcn_gather}

    def hop_bytes(self, topology) -> dict:
        """Per-device ring bytes under this plan's modes, split per
        fabric hop: ``{"ici": ..., "dcn": ...}``.  Exact buckets route
        hierarchically too (same tree, f32 gather) so the split is
        comparable across modes."""
        out = {"ici": 0.0, "dcn": 0.0}
        for b in self.buckets:
            bb = self._hop_bucket_bytes(b.mode, b.size, topology)
            out["ici"] += bb["ici"]
            out["dcn"] += bb["dcn"]
        return {k: int(v) for k, v in out.items()}

    def hop_bytes_exact(self, topology) -> dict:
        """Per-device ring bytes of the all-exact hierarchical tree —
        the denominator of the per-hop compression ratio."""
        out = {"ici": 0.0, "dcn": 0.0}
        for b in self.buckets:
            bb = self._hop_bucket_bytes("exact", b.size, topology)
            out["ici"] += bb["ici"]
            out["dcn"] += bb["dcn"]
        return {k: int(v) for k, v in out.items()}

    def hop_quant_bytes(self, topology) -> dict:
        """Per-device QUANTIZED payload bytes per hop.  The ICI hops
        are exact f32 by construction, so ``"ici"`` is identically 0 —
        the COMMBENCH "ICI exact" headline is this number."""
        S = topology.num_slices
        fd = (S - 1) / max(S, 1)
        dcn = 0.0
        for b in self.buckets:
            chunk = self._hier_chunk(b.size, topology)
            if b.mode == "int8":
                blocks = -(-chunk // self.config.block)
                dcn += fd * S * (chunk + 4 * blocks)
            elif b.mode == "bf16":
                dcn += fd * S * 2 * chunk
        return {"ici": 0, "dcn": int(dcn)}


def _flatten_float_leaves(tree: Any) -> list:
    """(keystr path, top-level key, leaf) for float leaves, flatten order."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for path, leaf in flat:
        dtype = getattr(leaf, "dtype", None) or np.asarray(leaf).dtype
        if not jnp.issubdtype(dtype, jnp.floating):
            continue
        top = ""
        if path and hasattr(path[0], "key"):
            top = str(path[0].key)
        out.append((jax.tree_util.keystr(path), top, leaf))
    return out


def plan_buckets(
    tree: Any, config: CommConfig, topology=None
) -> CommPlan:
    """Deterministic bucketing of a gradient/update tree.

    Leaves group by schedule stage (``stage_of`` on the top-level key),
    keep tree-flatten order within a stage, and pack greedily into
    buckets of at most ``bucket_mb``.  The assignment depends only on
    the tree structure and the config — NOT on the mesh size — so EF
    state saved at world N reshards to world M with the bucket
    composition unchanged (the checkpoint-elasticity requirement).
    Non-float leaves are excluded (they take the exact per-leaf path).

    With an ENGAGED hierarchical ``topology``
    (``config.hierarchical_with``): bucket capacity comes from
    ``dcn_bucket_mb`` (sized for the slow hop) and the bucket mode is
    the stage's DCN mode — the only hop that compresses.  The slice
    count does not influence composition, so the plan stays
    world-size-independent within one policy.
    """
    hier = config.hierarchical_with(topology)
    by_stage: dict[str, list] = {}
    for path, top, leaf in _flatten_float_leaves(tree):
        by_stage.setdefault(stage_of(top), []).append((path, leaf))
    buckets: list[Bucket] = []
    # Backward-completion order: heads first, backbone last (STAGES
    # reversed) — the order overlap issues collectives in.
    stage_order = [s for s in ("heads", "fpn", "backbone") if s in by_stage]
    cap = config.dcn_bucket_elems if hier else config.bucket_elems
    for stage in stage_order:
        pending: list[BucketLeaf] = []
        total = 0
        index = 0

        def flush():
            nonlocal pending, total, index
            if not pending:
                return
            mode = config.mode_for_stage(
                stage, config.effective_dcn_mode if hier else None
            )
            if mode == "none":
                # "none" (overlap-without-compression, or a per-stage
                # opt-out) means EXACT wire format — it must never fall
                # through to the quantizer.
                mode = "exact"
            if total * 4 < config.min_bucket_bytes:
                mode = "exact"  # wire saving is noise below this
            buckets.append(
                Bucket(
                    stage=stage, index=index, mode=mode,
                    leaves=tuple(pending), size=total,
                )
            )
            pending, total = [], 0
            index += 1

        for path, leaf in by_stage[stage]:
            size = int(np.prod(np.shape(leaf))) if np.shape(leaf) else 1
            if total and total + size > cap:
                flush()
            pending.append(
                BucketLeaf(
                    path=path, offset=total, size=size,
                    shape=tuple(int(d) for d in np.shape(leaf)),
                    dtype=str(np.dtype(getattr(leaf, "dtype", np.float32))),
                )
            )
            total += size
        flush()
    return CommPlan(buckets=tuple(buckets), config=config)


# ---------------------------------------------------------------------------
# EF state: init / partition specs (the opt_state-adjacent comm state)
# ---------------------------------------------------------------------------


def _padded_total(size: int, n: int) -> int:
    return n * (-(-size // n))


def bucket_state_key(bucket: Bucket, topology=None) -> str:
    """EF-state key of a bucket: ``"<stage>.<index>"`` on the flat
    tree, ``"<stage>.<index>@dcn"`` on the hierarchical tree — the
    residual lives on the hop that quantizes, and keying it per hop
    keeps a policy flip (flat <-> hierarchical) an explicit layout
    change (checkpoint ``ef_reset``) instead of a silent misread."""
    return bucket.key if topology is None else f"{bucket.key}@dcn"


def init_comm_state(
    params: Any,
    config: CommConfig,
    n: int,
    zero: bool = False,
    topology=None,
) -> dict:
    """Host-side zero EF state for ``params`` under ``config`` at world
    ``n``.  DP layout (``zero=False``): one flat ``(n * chunk,)`` f32
    residual per compressed bucket, keyed ``"<stage>.<index>"``.  ZeRO
    layout (``zero=True``): one flat residual per LEAF in the exact
    ZeRO storage layout (``(n * ceil(size/n),)``), keyed by the leaf's
    tree path — bucket composition then never constrains resharding.
    Hierarchical layout (an engaged ``topology``): one flat
    ``(n * hier_chunk,)`` residual per compressed bucket, keyed
    ``"<stage>.<index>@dcn"`` — thanks to the interleaved mesh
    convention (``parallel.mesh.CommTopology``) the array is in global
    bucket order with zero padding, so ``reshard_flat_leaf`` elasticity
    holds across world-size changes exactly like the flat layout.
    Empty dict when the policy carries no state."""
    if zero:
        topology = None  # the ZeRO update gather stays flat (ISSUE 16)
    hier = config.hierarchical_with(topology)
    if not hier:
        config = config.flat_equivalent(topology)
        topology = None
    if not config.needs_state:
        return {}
    plan = plan_buckets(params, config, topology)
    out: dict[str, np.ndarray] = {}
    for bucket in plan.buckets:
        if bucket.mode == "exact":
            continue
        if zero:
            for leaf in bucket.leaves:
                out[leaf.path] = np.zeros(
                    (_padded_total(leaf.size, n),), np.float32
                )
        elif topology is not None:
            chunk = plan._hier_chunk(bucket.size, topology)
            out[bucket_state_key(bucket, topology)] = np.zeros(
                (n * chunk,), np.float32
            )
        else:
            out[bucket.key] = np.zeros(
                (_padded_total(bucket.size, n),), np.float32
            )
    return out


def state_partition_specs(comm_state: Any) -> Any:
    """PartitionSpec tree for comm state: every residual is a flat array
    sharded on the data axis (device ``i`` owns the residual of the
    shard it quantizes); mirrors ``zero.opt_state_partition_specs``."""
    from jax.sharding import PartitionSpec as P

    return jax.tree.map(
        lambda l: P(DATA_AXIS) if getattr(l, "ndim", 0) >= 1 else P(),
        comm_state,
    )


# ---------------------------------------------------------------------------
# The per-bucket quantizer (shared by both collective layouts)
# ---------------------------------------------------------------------------


def _quantize_shard(
    shard: jnp.ndarray, mode: str, block: int
) -> tuple[Any, jnp.ndarray, jnp.ndarray]:
    """Quantize one reduced local shard; returns (payload, dequantized
    local shard, saturated-element count).  ``payload`` is what crosses
    the wire (int8 blocks + f32 scales, or a bf16 array)."""
    m = shard.shape[0]
    if mode == "bf16":
        q = shard.astype(jnp.bfloat16)
        deq = q.astype(jnp.float32)
        return q, deq, jnp.zeros((), jnp.float32)
    blocks = -(-m // block)
    sb = jnp.pad(shard, (0, blocks * block - m)).reshape(blocks, block)
    amax = jnp.max(jnp.abs(sb), axis=1)
    # Non-finite blocks poison their scale: the dequantized values go
    # NaN and the loop's finite-check aborts (never launder Inf into
    # finite int8 garbage — parallel/quantize.py's contract).
    scale = jnp.where(
        jnp.isfinite(amax), jnp.maximum(amax, 1e-30) / 127.0, jnp.nan
    )
    q = jnp.clip(jnp.round(sb / scale[:, None]), -127.0, 127.0).astype(
        jnp.int8
    )
    deq = (q.astype(jnp.float32) * scale[:, None]).reshape(-1)[:m]
    sat = jnp.sum((jnp.abs(q) >= 127).astype(jnp.float32))
    return (q, scale), deq, sat


def _dequantize_gathered(payload_all, mode: str, m: int, n: int):
    """All-gathered payload → the full ``(n * m,)`` f32 flat."""
    if mode == "bf16":
        return payload_all.astype(jnp.float32).reshape(-1)
    q_all, s_all = payload_all
    blocks_block = q_all.shape[1] * q_all.shape[2]
    return (
        (q_all.astype(jnp.float32) * s_all[..., None])
        .reshape(n, blocks_block)[:, :m]
        .reshape(-1)
    )


def _reduce_bucket_flat(
    flat: jnp.ndarray,
    res: jnp.ndarray | None,
    bucket: Bucket,
    config: CommConfig,
    axis_name: str,
    n: int,
):
    """One bucket's compressed pmean (call inside shard_map).

    ``flat`` is the local (pre-reduce) logical concat of the bucket's
    leaves; ``res`` the local EF residual slice or None.  Returns
    (reduced full flat (size,), new local residual | None, sat count).
    """
    size = bucket.size
    if bucket.mode == "exact":
        return lax.pmean(flat, axis_name), res, jnp.zeros((), jnp.float32)
    padded = _pad_flat(flat, n)
    # Phase 1: exact f32 reduction — each device owns 1/n of the sum.
    shard = lax.psum_scatter(padded, axis_name, tiled=True) / n
    if res is not None:
        shard = shard + res  # EF add-back: last step's dropped rounding
    payload, deq_local, sat = _quantize_shard(
        shard, bucket.mode, config.block
    )
    new_res = (shard - deq_local) if res is not None else None
    # Phase 2: compressed gather — every device dequantizes the same
    # bytes, so the result stays bitwise replicated.
    if bucket.mode == "bf16":
        gathered = lax.all_gather(payload, axis_name)
    else:
        gathered = (
            lax.all_gather(payload[0], axis_name),
            lax.all_gather(payload[1], axis_name),
        )
    out = _dequantize_gathered(gathered, bucket.mode, shard.shape[0], n)
    return out[:size], new_res, sat


def reduce_bucket_hierarchical(
    flat: jnp.ndarray,
    res: jnp.ndarray | None,
    bucket: Bucket,
    config: CommConfig,
    axis_name: str,
    topology,
):
    """One bucket's pmean through the two-fabric hierarchical tree
    (call inside shard_map; ISSUE 16).

    Five phases, compression ONLY on the slow hop:

    1. ICI reduce-scatter (exact f32, grouped per slice): intra-slice
       rank ``r`` owns tile ``r`` of the slice-local sum;
    2. DCN reduce-scatter (exact f32, grouped per rank): slice ``s``
       owns tile ``s`` of the GLOBAL sum — with the interleaved mesh
       convention that tile is exactly ``[d * chunk, (d+1) * chunk)``
       of the bucket flat for mesh position ``d``;
    3. EF add-back + quantize of the owned chunk (``bucket.mode``);
    4. DCN all-gather of the quantized payload: every device in the
       rank group dequantizes the same bytes — the reconstructed ICI
       tile is bitwise identical across slices;
    5. ICI all-gather (exact f32) of the tiles back to the full bucket.

    Returns (reduced full flat ``(size,)``, new local DCN-hop residual
    or None, saturated-element count)."""
    size = bucket.size
    if bucket.mode == "exact":
        return lax.pmean(flat, axis_name), res, jnp.zeros((), jnp.float32)
    S, L = topology.num_slices, topology.slice_size
    n = topology.num_devices
    ici_groups = topology.ici_groups()
    dcn_groups = topology.dcn_groups()
    padded = _pad_flat(flat, L)
    tile = lax.psum_scatter(
        padded, axis_name, tiled=True, axis_index_groups=ici_groups
    )
    tile_padded = _pad_flat(tile, S)
    shard = (
        lax.psum_scatter(
            tile_padded, axis_name, tiled=True, axis_index_groups=dcn_groups
        )
        / n
    )
    if res is not None:
        shard = shard + res  # EF add-back: last step's dropped rounding
    payload, deq_local, sat = _quantize_shard(
        shard, bucket.mode, config.block
    )
    new_res = (shard - deq_local) if res is not None else None
    if bucket.mode == "bf16":
        gathered = lax.all_gather(
            payload, axis_name, axis_index_groups=dcn_groups
        )
    else:
        gathered = (
            lax.all_gather(
                payload[0], axis_name, axis_index_groups=dcn_groups
            ),
            lax.all_gather(
                payload[1], axis_name, axis_index_groups=dcn_groups
            ),
        )
    tile_out = _dequantize_gathered(
        gathered, bucket.mode, shard.shape[0], S
    )[: tile.shape[0]]
    full = lax.all_gather(
        tile_out, axis_name, tiled=True, axis_index_groups=ici_groups
    )
    return full[:size], new_res, sat


# ---------------------------------------------------------------------------
# DP path: reduce_tree (the bucketed, EF'd pmean)
# ---------------------------------------------------------------------------


def _leaf_map(tree: Any) -> tuple[dict, Any]:
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    return {jax.tree_util.keystr(p): l for p, l in flat}, (flat, treedef)


def _rebuild(tree: Any, out_map: Mapping[str, Any]) -> Any:
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    leaves = [
        out_map.get(jax.tree_util.keystr(p), l) for p, l in flat
    ]
    return jax.tree_util.tree_unflatten(treedef, leaves)


def reduce_leaves(
    leaf_map: Mapping[str, jnp.ndarray],
    res_map: Mapping[str, jnp.ndarray],
    buckets,
    config: CommConfig,
    axis_name: str,
    n: int,
    topology=None,
):
    """Reduce the leaves of ``buckets`` (a leaf-path → local-grad map);
    the shared engine under ``reduce_tree`` and the overlap taps.
    ``topology`` non-None selects the hierarchical tree (callers pass
    it ONLY when the hierarchy actually engages — the flat fallback
    must stay byte-identical HLO).
    Returns (reduced leaf map, new residual map, saturation count)."""
    out: dict[str, jnp.ndarray] = {}
    new_res: dict[str, jnp.ndarray] = {}
    sat_total = jnp.zeros((), jnp.float32)
    for bucket in buckets:
        parts = []
        for leaf in bucket.leaves:
            g = leaf_map[leaf.path]
            parts.append(g.astype(jnp.float32).reshape(-1))
        flat = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
        key = bucket_state_key(bucket, topology)
        res = res_map.get(key) if bucket.mode != "exact" else None
        if topology is not None:
            reduced, res_out, sat = reduce_bucket_hierarchical(
                flat, res, bucket, config, axis_name, topology
            )
        else:
            reduced, res_out, sat = _reduce_bucket_flat(
                flat, res, bucket, config, axis_name, n
            )
        sat_total = sat_total + sat
        if res_out is not None:
            new_res[key] = res_out
        for leaf in bucket.leaves:
            piece = lax.dynamic_slice(
                reduced, (leaf.offset,), (leaf.size,)
            )
            out[leaf.path] = piece.reshape(leaf.shape).astype(
                leaf_map[leaf.path].dtype
            )
    return out, new_res, sat_total


def reduce_tree(
    grads: Any,
    comm_state: Mapping[str, jnp.ndarray],
    plan: CommPlan,
    config: CommConfig,
    axis_name: str = DATA_AXIS,
    n: int = 1,
    topology=None,
):
    """Bucketed compressed pmean of a whole gradient tree (the fused,
    overlap-off path; call inside shard_map).  Non-float leaves take
    the exact per-leaf pmean.  ``topology`` non-None selects the
    hierarchical tree (see ``reduce_bucket_hierarchical``); callers
    resolve the flat fallback BEFORE tracing.  Returns (reduced tree,
    new comm state, local saturation count)."""
    leaf_map, _ = _leaf_map(grads)
    planned = {l.path for b in plan.buckets for l in b.leaves}
    out_map, new_res, sat = reduce_leaves(
        leaf_map, comm_state, plan.buckets, config, axis_name, n, topology
    )
    for path, leaf in leaf_map.items():
        if path not in planned:
            out_map[path] = lax.pmean(leaf, axis_name)
    # Preserve the comm-state STRUCTURE exactly (a key a bucket did not
    # update — e.g. EF off for that bucket — passes through unchanged),
    # so the step can replace state.comm_state wholesale.
    new_res = {k: new_res.get(k, v) for k, v in comm_state.items()}
    return _rebuild(grads, out_map), new_res, sat


def bucketed_pmean(grads: Any, axis_name: str, n: int, config=None):
    """Stateless (no-EF) bucketed compressed pmean — the drop-in for the
    deprecated ``parallel/quantize.quantized_pmean`` alias.  Builds the
    plan at trace time from the tree itself."""
    config = config or CommConfig(compress="int8", error_feedback=False)
    plan = plan_buckets(grads, config)
    reduced, _, _ = reduce_tree(grads, {}, plan, config, axis_name, n)
    return reduced


# ---------------------------------------------------------------------------
# ZeRO path: compressed update gather
# ---------------------------------------------------------------------------


def zero_gather_updates(
    updates: Any,
    params: Any,
    comm_state: Mapping[str, jnp.ndarray],
    plan: CommPlan,
    config: CommConfig,
    axis_name: str = DATA_AXIS,
    n: int = 1,
):
    """Replace ZeRO's f32 param all-gather with a compressed UPDATE
    gather (call inside shard_map).

    ``updates`` is the optax update tree in local ZeRO shards (one
    ``(chunk_leaf,)`` slice per leaf, ``parallel/zero.sharded_update``
    layout); ``params`` the replicated full params.  Per bucket: concat
    the member leaves' update shards, EF add-back from the per-leaf
    residual slices, quantize, all-gather, and apply the identical
    dequantized full update to the replicated params.  Exact buckets
    gather in f32 (bitwise ZeRO-classic for that bucket).  Returns
    (new_params, new comm state, saturation count).
    """
    upd_map, _ = _leaf_map(updates)
    param_map, _ = _leaf_map(params)
    new_params_map: dict[str, jnp.ndarray] = {}
    new_res: dict[str, jnp.ndarray] = {}
    sat_total = jnp.zeros((), jnp.float32)
    planned = {l.path for b in plan.buckets for l in b.leaves}
    for bucket in plan.buckets:
        shards = [
            upd_map[l.path].astype(jnp.float32).reshape(-1)
            for l in bucket.leaves
        ]
        chunks = [s.shape[0] for s in shards]
        flat = shards[0] if len(shards) == 1 else jnp.concatenate(shards)
        # EF engages iff the caller's state carries EVERY member leaf's
        # residual (the step.py contract) — a stateless caller (the
        # deprecated alias, or a policy flip before init_comm_state)
        # degrades to no-EF quantization instead of a trace-time error.
        use_ef = (
            bucket.mode != "exact"
            and config.needs_state
            and all(l.path in comm_state for l in bucket.leaves)
        )
        res = None
        if use_ef:
            res_parts = [comm_state[l.path] for l in bucket.leaves]
            res = (
                res_parts[0]
                if len(res_parts) == 1
                else jnp.concatenate(res_parts)
            )
        if bucket.mode == "exact":
            gathered = lax.all_gather(flat, axis_name)  # (n, L) f32
            sat = jnp.zeros((), jnp.float32)
        else:
            if res is not None:
                flat = flat + res
            payload, deq_local, sat = _quantize_shard(
                flat, bucket.mode, config.block
            )
            if res is not None:
                res_out = flat - deq_local
                off = 0
                for leaf, c in zip(bucket.leaves, chunks):
                    new_res[leaf.path] = lax.dynamic_slice(
                        res_out, (off,), (c,)
                    )
                    off += c
            if bucket.mode == "bf16":
                gathered = lax.all_gather(payload, axis_name).astype(
                    jnp.float32
                )
            else:
                q_all = lax.all_gather(payload[0], axis_name)
                s_all = lax.all_gather(payload[1], axis_name)
                gathered = (
                    q_all.astype(jnp.float32) * s_all[..., None]
                ).reshape(n, -1)[:, : flat.shape[0]]
        sat_total = sat_total + sat
        # Reassemble each leaf's full update from its column range of
        # the gathered (n, L) matrix: full = interleave of device
        # shards in logical order (the ZeRO flat layout).
        off = 0
        for leaf, c in zip(bucket.leaves, chunks):
            cols = lax.dynamic_slice(
                gathered, (0, off), (n, c)
            ).reshape(n * c)[: leaf.size]
            p = param_map[leaf.path]
            new_params_map[leaf.path] = (
                p + cols.reshape(leaf.shape).astype(p.dtype)
            )
            off += c
    # Leaves outside the plan (non-float — none in practice) gather f32.
    for path, p in param_map.items():
        if path not in planned:
            shard = upd_map[path]
            full = lax.all_gather(shard, axis_name, tiled=True)
            new_params_map[path] = p + full[: p.size].reshape(p.shape).astype(
                p.dtype
            )
    # Structure-preserving state replacement (see reduce_tree).
    new_res = {k: new_res.get(k, v) for k, v in comm_state.items()}
    return _rebuild(params, new_params_map), new_res, sat_total


# ---------------------------------------------------------------------------
# In-step health metrics (the obs wiring)
# ---------------------------------------------------------------------------


def comm_metrics(
    plan: CommPlan,
    new_comm_state: Mapping[str, jnp.ndarray],
    sat_local: jnp.ndarray,
    axis_name: str,
    n: int,
    zero: bool = False,
    topology=None,
) -> dict[str, jnp.ndarray]:
    """EF health metrics for the step's metrics dict (call inside
    shard_map, after the reduce): global residual norm, global scale
    saturation fraction, and the plan's static bytes-on-wire.
    ``zero`` selects the ZeRO layout's saturation denominator.

    Hierarchical runs (``topology``) split the static accounting per
    hop — ``comm_ici_bytes`` / ``comm_dcn_bytes`` — and label the
    residual norm with its hop (``ef_residual_norm_dcn``; all
    hierarchical residuals live on the DCN hop) so a DCN-only blow-up
    is attributable (the per-hop ``ef_residual_spike`` SLO rule).  The
    hop-agnostic keys stay for dashboard continuity."""
    if topology is not None:
        hop = plan.hop_bytes(topology)
        out: dict[str, jnp.ndarray] = {
            "comm_compressed_bytes": jnp.asarray(
                float(hop["ici"] + hop["dcn"]), jnp.float32
            ),
            "comm_ici_bytes": jnp.asarray(float(hop["ici"]), jnp.float32),
            "comm_dcn_bytes": jnp.asarray(float(hop["dcn"]), jnp.float32),
        }
    else:
        out = {
            "comm_compressed_bytes": jnp.asarray(
                float(plan.compressed_bytes(n)), jnp.float32
            ),
        }
    denom = float(
        max(1, n * plan.quant_elems(n, zero=zero, topology=topology))
    )
    out["ef_saturation"] = lax.psum(sat_local, axis_name) / denom
    if new_comm_state:
        sq = sum(
            jnp.sum(jnp.square(r)) for r in new_comm_state.values()
        )
        out["ef_residual_norm"] = jnp.sqrt(lax.psum(sq, axis_name))
        if topology is not None:
            out["ef_residual_norm_dcn"] = out["ef_residual_norm"]
    return out
