"""comm/: the gradient-communication subsystem (ISSUE 13).

Owns how gradients (and ZeRO weight updates) cross the interconnect:

- ``config.CommConfig`` — the policy layer (compress mode, error
  feedback, overlap, bucket sizing, per-stage overrides) that replaced
  the bare ``quantized_allreduce`` bool;
- ``compress`` — bucketed int8/bf16 collectives with error feedback,
  the DP ``reduce_tree`` and the ZeRO ``zero_gather_updates`` layouts,
  EF-state init/partition rules, and the static bytes-on-wire plan the
  COMMBENCH artifact measures against;
- ``overlap`` — custom-VJP staging that issues each schedule stage's
  compressed collective from inside the backward pass.

Consumers: ``train/step.py`` (both mesh step flavors),
``utils/cli.py``/``train.py`` (flag surface), ``bench.py --mode comm``
(COMMBENCH), ``obs/`` (EF health gauges + the ``ef_residual_spike``
SLO rule), and the collective-safety lint rule (this package's public
reducers are collective call sites).
"""

from batchai_retinanet_horovod_coco_tpu.comm.config import (
    CommConfig,
    STAGES,
    stage_of,
)
from batchai_retinanet_horovod_coco_tpu.comm.compress import (
    CommPlan,
    bucket_state_key,
    bucketed_pmean,
    comm_metrics,
    init_comm_state,
    plan_buckets,
    reduce_bucket_hierarchical,
    reduce_tree,
    state_partition_specs,
    zero_gather_updates,
)

__all__ = [
    "STAGES",
    "CommConfig",
    "CommPlan",
    "bucket_state_key",
    "bucketed_pmean",
    "comm_metrics",
    "init_comm_state",
    "plan_buckets",
    "reduce_bucket_hierarchical",
    "reduce_tree",
    "stage_of",
    "state_partition_specs",
    "zero_gather_updates",
]
