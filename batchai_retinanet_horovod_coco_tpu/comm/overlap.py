"""Comm/compute overlap: issue each stage's collective INSIDE backward.

With the fused path (comm/compress.reduce_tree) every gradient collective
runs after the whole backward pass has finished.  XLA can hide some of
that behind compute, but the schedule is one monolithic block at the end
of the step.  This module restructures WHERE the collectives appear in
the autodiff graph instead: each schedule stage's parameters pass through
an identity "tap" whose custom VJP performs that stage's bucketed
compressed reduce on the cotangents — so the heads' all-reduce is
emitted (and can be scheduled by XLA) the moment the heads' gradients
exist, while the backbone's backward is still running.  Backward-
completion order is heads → fpn → backbone (the reverse of forward), so
the deepest stage's (largest) collective is the only one that cannot
overlap with anything.

Staging is ``jax.remat``-safe by construction: ``jax.custom_vjp`` is the
one AD primitive remat treats as opaque-and-replayable, so a remat'd
forward re-runs the identity tap (free) and the collective still fires
exactly once, in the backward.

State threading through a custom VJP (which cannot return side
outputs) uses the cotangent channel itself:

- the EF residual enters as a PRIMAL input whose "gradient" IS the new
  residual (the bwd returns it as that input's cotangent), so
  ``jax.grad(..., argnums=(params, residuals, token))`` hands the step
  the post-quantization EF state with no side channel;
- a zero scalar "token" input's cotangent carries the stage's
  saturated-element count the same way.

The quantization math is byte-for-byte the shared
``compress.reduce_leaves`` — overlap-on and overlap-off produce the
same values (pinned by tests/unit/test_comm.py), only the schedule
differs.
"""

from __future__ import annotations

from typing import Any, Callable, Mapping

import jax
import jax.numpy as jnp
from jax import lax

from batchai_retinanet_horovod_coco_tpu.comm.compress import (
    CommPlan,
    bucket_state_key,
    reduce_leaves,
)
from batchai_retinanet_horovod_coco_tpu.comm.config import (
    CommConfig,
    stage_of,
)


def group_tree(params: Any, plan: CommPlan) -> dict[str, Any]:
    """Split a params tree into per-stage subtrees ({stage: {top: sub}}).

    Every top-level key lands in exactly one stage (non-Mapping trees
    collapse into a single "heads" group), so the union of the groups
    is the whole tree and ``merge_groups`` is the exact inverse."""
    if not isinstance(params, Mapping):
        return {"heads": {"__root__": params}}
    groups: dict[str, dict] = {}
    for key in params:
        groups.setdefault(stage_of(key), {})[key] = params[key]
    return groups


def merge_groups(params: Any, groups: Mapping[str, Any]) -> Any:
    """Inverse of ``group_tree`` (same leaf objects, original shape)."""
    if not isinstance(params, Mapping):
        return groups["heads"]["__root__"]
    merged = {}
    for sub in groups.values():
        merged.update(sub)
    return {k: merged[k] for k in params}


def _stage_leaf_map(sub: Any, raw_root: bool) -> dict[str, Any]:
    """Leaf-path → leaf map whose paths match the FULL-tree plan paths
    (compress.py's keyed flatten, minus the ``__root__`` wrapper)."""
    from batchai_retinanet_horovod_coco_tpu.comm.compress import _leaf_map

    leaf_map, _ = _leaf_map(sub["__root__"] if raw_root else sub)
    return leaf_map


def _rebuild_stage(sub: Any, raw_root: bool, out_map: Mapping[str, Any]):
    from batchai_retinanet_horovod_coco_tpu.comm.compress import _rebuild

    rebuilt = _rebuild(sub["__root__"] if raw_root else sub, out_map)
    return {"__root__": rebuilt} if raw_root else rebuilt


def make_stage_tap(
    stage: str,
    plan: CommPlan,
    config: CommConfig,
    axis_name: str,
    n: int,
    raw_root: bool,
    topology=None,
) -> Callable:
    """Identity on a stage's params whose VJP reduces the cotangents.

    ``tap(params_sub, res_sub, token) -> params_sub``; under ``grad``
    the cotangents are (reduced grads, new EF residuals, saturation
    count) — see the module docstring's cotangent-channel contract.
    ``topology`` non-None stages the HIERARCHICAL reduce (exact ICI,
    compressed DCN) instead of the flat one — same shared engine
    (``reduce_leaves``), so overlap-on/off parity holds per hop too."""
    buckets = plan.stage_buckets(stage)
    bucket_paths = {l.path for b in buckets for l in b.leaves}

    @jax.custom_vjp
    def tap(params_sub, res_sub, token):
        del res_sub, token
        return params_sub

    def fwd(params_sub, res_sub, token):
        del token
        return params_sub, res_sub

    def bwd(res_sub, ct):
        leaf_map = _stage_leaf_map(ct, raw_root)
        out_map, new_res, sat = reduce_leaves(
            leaf_map, res_sub, buckets, config, axis_name, n, topology
        )
        # Non-bucketed leaves of this stage (non-float) reduce exact.
        for path, leaf in leaf_map.items():
            if path not in bucket_paths:
                out_map[path] = lax.pmean(leaf, axis_name)
        reduced = _rebuild_stage(ct, raw_root, out_map)
        # The residual cotangent must mirror res_sub's structure
        # exactly (exact buckets carry no state and pass through).
        res_out = {k: new_res.get(k, v) for k, v in res_sub.items()}
        return reduced, res_out, sat

    tap.defvjp(fwd, bwd)
    return tap


def make_overlap_grad_fn(
    plan: CommPlan, config: CommConfig, axis_name: str, n: int,
    topology=None,
) -> Callable:
    """Build ``grad_fn(loss_of_params, params, comm_state)`` returning
    ``((loss, aux), reduced_grads, new_comm_state, sat_count)`` with the
    per-stage collectives staged inside the backward pass.  With
    ``topology`` each stage's collective is the hierarchical tree and
    the EF residuals use the per-hop keys (``bucket_state_key``)."""
    def grad_fn(loss_of_params, params, comm_state):
        raw_root = not isinstance(params, Mapping)
        groups = group_tree(params, plan)
        taps = {
            s: make_stage_tap(
                s, plan, config, axis_name, n, raw_root, topology
            )
            for s in groups
        }
        res_groups = {
            s: {
                bucket_state_key(b, topology): comm_state[
                    bucket_state_key(b, topology)
                ]
                for b in plan.stage_buckets(s)
                if bucket_state_key(b, topology) in comm_state
            }
            for s in groups
        }
        tokens = {s: jnp.zeros((), jnp.float32) for s in groups}

        def wrapped(groups_in, res_in, tokens_in):
            tapped = {
                s: taps[s](groups_in[s], res_in[s], tokens_in[s])
                for s in groups_in
            }
            return loss_of_params(merge_groups(params, tapped))

        (loss, aux), (g_groups, g_res, g_tok) = jax.value_and_grad(
            wrapped, argnums=(0, 1, 2), has_aux=True
        )(groups, res_groups, tokens)
        grads = merge_groups(params, g_groups)
        new_comm = {
            k: v for s in g_res for k, v in g_res[s].items()
        }
        sat = sum(g_tok.values(), jnp.zeros((), jnp.float32))
        return (loss, aux), grads, new_comm, sat

    return grad_fn
