"""Runtime lock-order witness (ISSUE 20) — jax-free, stdlib only.

The static ``lock-order`` rule (analysis/rules/lock_graph.py) computes the
may-hold-while-acquiring graph and commits it as
``analysis/lock_order.json``.  This module is the dynamic half: under
``RETINANET_LOCK_DEBUG=1`` (on by default in tier-1 and the chaos/fleet/
stream/scale smokes), ``make_lock("<identity>")`` returns a debug wrapper
that records each thread's real acquisition order and RAISES
``LockOrderViolation`` on any inversion of the committed order — so the
committed graph is validated by every smoke run instead of rotting.

With the flag off, ``make_lock``/``make_rlock`` return plain
``threading.Lock``/``RLock`` objects: the witness is identity and costs
nothing (PARITY §5.21).

Semantics when enabled:

- Acquiring ``B`` while holding ``A`` raises iff the committed order
  contains the REVERSE edge ``B -> A`` (i.e. the tree's sanctioned order
  says B-before-A).  Pairs absent from the committed order are recorded
  (``observed_edges()``) but never raise — the static pass, not the
  witness, decides whether a new edge is acceptable.
- Re-entrant acquisition of a lock already held by this thread (RLock
  reentry, ``Condition._is_owned`` probes) is never checked.
- Identities come from the ``make_lock`` name literal, which is exactly
  what the static rule uses, so the two halves agree by construction.
"""

from __future__ import annotations

import json
import os
import threading

ENV_FLAG = "RETINANET_LOCK_DEBUG"
#: Override the committed-order file (tests / fixture trees).
ENV_ORDER = "RETINANET_LOCK_ORDER"


class LockOrderViolation(RuntimeError):
    """A thread acquired locks against the committed static order."""


def enabled() -> bool:
    return os.environ.get(ENV_FLAG, "") == "1"


def default_order_path() -> str:
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(pkg, "analysis", "lock_order.json")


_state_lock = threading.Lock()
_committed: set[tuple[str, str]] | None = None  # (src held, dst acquired)
_observed: set[tuple[str, str]] = set()
_tls = threading.local()


def _committed_edges() -> set[tuple[str, str]]:
    global _committed
    with _state_lock:
        if _committed is None:
            path = os.environ.get(ENV_ORDER) or default_order_path()
            edges: set[tuple[str, str]] = set()
            if os.path.exists(path):
                with open(path) as f:
                    data = json.load(f)
                edges = {(e["src"], e["dst"])
                         for e in data.get("edges", [])}
            _committed = edges
        return _committed


def _set_committed_for_testing(
        edges: set[tuple[str, str]] | None) -> None:
    """Tests inject a committed order without touching the filesystem;
    pass None to reload from disk on next use."""
    global _committed
    with _state_lock:
        _committed = set(edges) if edges is not None else None


def observed_edges() -> list[tuple[str, str]]:
    """Every (held, acquired) pair actually witnessed so far."""
    with _state_lock:
        return sorted(_observed)


def reset_observed() -> None:
    with _state_lock:
        _observed.clear()


def _held_stack() -> list[str]:
    stack = getattr(_tls, "held", None)
    if stack is None:
        stack = _tls.held = []
    return stack


def _check_order(name: str) -> None:
    held = _held_stack()
    if not held or name in held:
        return
    committed = _committed_edges()
    new_pairs = [(h, name) for h in held]
    for h, n in new_pairs:
        if (n, h) in committed:
            chain = " -> ".join(held + [name])
            raise LockOrderViolation(
                f"lock-order inversion: thread "
                f"{threading.current_thread().name!r} acquiring {name!r} "
                f"while holding {h!r}; its chain is [{chain}] but the "
                f"committed order (analysis/lock_order.json) has the "
                f"chain {name!r} -> {h!r} ({name!r} before {h!r}). "
                f"Fix the acquisition order or re-run "
                f"--update-lock-order after review."
            )
    with _state_lock:
        _observed.update(new_pairs)


class _DebugLockBase:
    """Shared acquire/release bookkeeping for the Lock/RLock wrappers."""

    def __init__(self, name: str, inner):
        self.name = name
        self._inner = inner

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        _check_order(self.name)
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            _held_stack().append(self.name)
        return ok

    def release(self) -> None:
        held = _held_stack()
        # Pop the LAST occurrence: RLock reentry releases innermost-first.
        for i in range(len(held) - 1, -1, -1):
            if held[i] == self.name:
                del held[i]
                break
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<{type(self).__name__} {self.name!r} of {self._inner!r}>"


class DebugLock(_DebugLockBase):
    pass


class DebugRLock(_DebugLockBase):
    # threading.Condition duck-types on these when given a custom lock.
    def _release_save(self):
        return self._inner._release_save()  # pragma: no cover

    def _acquire_restore(self, state):  # pragma: no cover
        return self._inner._acquire_restore(state)

    def _is_owned(self):  # pragma: no cover
        return self._inner._is_owned()


def make_lock(name: str):
    """A ``threading.Lock`` — wrapped by the order witness when
    ``RETINANET_LOCK_DEBUG=1``.  ``name`` is the dotted lock identity the
    static ``lock-order`` rule uses (``serve.fleet.FleetRouter._lock``)."""
    if enabled():
        return DebugLock(name, threading.Lock())
    return threading.Lock()


def make_rlock(name: str):
    """``make_lock`` for re-entrant locks."""
    if enabled():
        return DebugRLock(name, threading.RLock())
    return threading.RLock()
