"""Crash-safe async checkpoint/resume, world-size-elastic (ISSUE 11).

Reference behavior being replaced (SURVEY.md §5.4): Keras ``ModelCheckpoint``
on rank 0 wrote one full-model ``.h5`` per epoch WITHOUT optimizer state, so
resume restarted the optimizer.  Rounds 1-10 used orbax for the full train
state; this module replaces it with a native writer because orbax's async
finalize thread (cross-thread asyncio wakeups + grpc) segfaulted under
sandboxed kernels, forcing tests — and any similar production host — onto
the synchronous path, and because its storage format pinned a ZeRO-sharded
optimizer state to the world size that wrote it.

**Format** — one directory per checkpoint, scanned (never indexed):

    <dir>/ckpt-<step>/
        leaf_00000.npy ...    # tree leaves, keypath order
        manifest.json         # committed LAST: keypaths, shapes, dtypes,
                              # sizes, crc32s, zero_world_size, metadata

**Crash-safety protocol** (the whole point): leaves are written into
``<dir>/.tmp-<step>-<pid>`` and fsync'd; the manifest is written (and
fsync'd) into the tmp dir LAST; one atomic ``os.rename`` publishes the
directory; the parent directory is fsync'd after.  A ``SIGKILL`` at ANY
instant therefore leaves either the previous complete checkpoint or the
new one — a dir without a manifest, or whose manifest disagrees with its
files, is torn by definition and the restore scan skips it (one
structured ``ckpt_torn_skipped`` stderr line, then the next-newest valid
checkpoint).  ``scripts/chaos.py`` kills a real training subprocess at
every phase of this protocol and asserts exactly that.

**Async contract** — ``save()`` snapshots device→host synchronously in
the caller's thread (the training loop's step serialization is the step
lock: the snapshot sees exactly the state at the save step) and hands the
host tree to ONE long-lived background writer thread, so the disk write
overlaps subsequent train steps.  Bounded one-behind: a new save first
joins the previous in-flight write, so at most one checkpoint of host
memory is ever pinned and saves can never stack.  The writer is
watchdog-registered, spans its work (``ckpt_write``), feeds the telemetry
gauges (``ckpt_save_s`` / ``ckpt_inflight`` / ``ckpt_last_success_age_s``,
obs/telemetry.py — the staleness SLO rule watches the age), and carries
the shm-pipeline error contract: a writer crash is announced on stderr
and re-raised in the training loop at the next ``save()``/``wait()``/
``close()``.  ``RETINANET_ASYNC_CKPT=0`` remains as an escape hatch
selecting the synchronous in-caller-thread path (same protocol, no
thread).

**World-size elasticity** — the pytree structure of a ZeRO-sharded
optimizer state equals the replicated one (parallel/zero.py); only leaf
shapes differ, and the flat layout's padding is zeros.  Leaves are saved
in whatever layout the run used, keyed by tree path, and ``restore()``
re-lays each optimizer leaf into the TEMPLATE's layout via
``zero.reshard_flat_leaf`` — so a checkpoint written at world size N
restores at world size M (N ≠ M in either direction, including M = 1:
replicated single-host recovery of a pod checkpoint).  Params/batch
stats/step require exact shape+dtype (a mismatch there is a different
model, never a resharding problem).

Multi-host: every process calls ``save()`` (non-addressable sharded
leaves are gathered collectively), process 0 writes.  Deliberate trade:
the gather costs one all-gather of the ZeRO optimizer state per save —
at pod save cadences (O(1000) steps) that is noise, and it is what buys
the world-free on-disk layout; per-process shard files (restore already
re-lays arbitrary flat layouts) are the future optimization if a profile
ever blames checkpoint-interval network.
"""

from __future__ import annotations

import dataclasses
import json
import os
import queue
import re
import shutil
import signal
import sys
import threading
import zlib
from typing import Any, Callable, Mapping

import jax
import numpy as np

from batchai_retinanet_horovod_coco_tpu.obs import telemetry, trace, watchdog
from batchai_retinanet_horovod_coco_tpu.obs.trace import monotonic_s
from batchai_retinanet_horovod_coco_tpu.parallel.zero import reshard_flat_leaf
from batchai_retinanet_horovod_coco_tpu.train.state import TrainState
from batchai_retinanet_horovod_coco_tpu.utils.atomicio import (
    atomic_write_json,
    fsync_dir,
)

FORMAT = "retinanet-ckpt"
FORMAT_VERSION = 1

_STEP_DIR_RE = re.compile(r"^ckpt-(\d+)$")
_TMP_PREFIX = ".tmp-"

# Production default: async (the write overlaps train steps).
# RETINANET_ASYNC_CKPT=0 selects the synchronous path — kept as an escape
# hatch for debugging; the native writer is plain stdlib threading (no
# asyncio, no grpc), so the orbax finalize-segfault class that forced the
# test env onto this path is gone and tests run async like production.


def _async_default() -> bool:
    return os.environ.get("RETINANET_ASYNC_CKPT", "1").lower() not in (
        "0", "false",
    )


# ---------------------------------------------------------------------------
# Fault-injection hooks (scripts/chaos.py)
# ---------------------------------------------------------------------------

# RETINANET_CHAOS_KILL="<phase>@<n>": SIGKILL this process at the n-th
# (1-based) crossing of the named save phase.  Phases, in protocol order:
# snapshot, tmp_write, manifest_commit, rename, finalize.  Counters are
# per-process; the chaos harness schedules one (phase, n) per subprocess
# so every kill lands at a known protocol point.
_chaos_counts: dict[str, int] = {}


def _chaos_point(phase: str) -> None:
    spec = os.environ.get("RETINANET_CHAOS_KILL")
    if not spec:
        return
    name, _, n = spec.partition("@")
    if name != phase:
        return
    _chaos_counts[phase] = _chaos_counts.get(phase, 0) + 1
    if _chaos_counts[phase] == int(n or 1):
        print(
            json.dumps({"event": "chaos_kill", "phase": phase,
                        "occurrence": _chaos_counts[phase]}),
            file=sys.stderr, flush=True,
        )
        sys.stderr.flush()
        os.kill(os.getpid(), signal.SIGKILL)


# ---------------------------------------------------------------------------
# Tree <-> flat leaves
# ---------------------------------------------------------------------------


def _saveable(state: TrainState) -> dict[str, Any]:
    """The pytree that goes to disk (drops the static optax transform).

    ``comm_state`` (ISSUE 13: gradient-compression EF residuals) rides
    along; it is ``()`` — zero leaves, manifest unchanged — for every
    run without compression, so pre-ISSUE-13 checkpoints and
    uncompressed runs keep the exact same on-disk leaf set."""
    return {
        "step": state.step,
        "params": state.params,
        "batch_stats": state.batch_stats,
        "opt_state": state.opt_state,
        "comm_state": getattr(state, "comm_state", ()),
    }


def _flatten_with_paths(tree: Any) -> list[tuple[str, Any]]:
    """(stable keypath string, leaf) pairs — the on-disk leaf identity.

    The keypath strings are ``jax.tree_util.keystr`` output; a sharded and
    a replicated opt_state flatten to the SAME paths (same treedef), which
    is what lets restore re-lay layouts leaf-by-leaf.
    """
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [(jax.tree_util.keystr(path), leaf) for path, leaf in flat]


def _detect_zero_world(opt_state: Any) -> int | None:
    """World size of a ZeRO-sharded opt_state (None = replicated layout),
    read off the leaves' NamedSharding specs (the storage-format rule,
    parallel/zero.py::opt_state_partition_specs)."""
    for leaf in jax.tree_util.tree_leaves(opt_state):
        sharding = getattr(leaf, "sharding", None)
        spec = getattr(sharding, "spec", None)
        if spec is not None and any(axis is not None for axis in spec):
            return int(sharding.mesh.size)
    return None


_gather_jits: dict[Any, Callable] = {}


def _replicate_global(x: Any) -> Any:
    """Reshard one globally-sharded array to fully-replicated via a jit
    identity (compiles to one all-gather; every process participates).
    One jit per mesh — jax caches the per-shape executables under it."""
    from jax.sharding import NamedSharding, PartitionSpec

    mesh = x.sharding.mesh
    fn = _gather_jits.get(mesh)
    if fn is None:
        fn = _gather_jits[mesh] = jax.jit(
            lambda a: a, out_shardings=NamedSharding(mesh, PartitionSpec())
        )
    return fn(x)


def _host_leaf(x: Any) -> np.ndarray:
    """One leaf device→host, as an OWNED copy.  Non-fully-addressable
    arrays (cross-host ZeRO shards) are gathered collectively — every
    process must be inside ``save()`` when this runs (they are: save is
    called loop-side on all processes, like the orbax contract it
    replaces).

    The copy is load-bearing, not defensive: on the CPU backend
    ``device_get`` returns ZERO-COPY views of device buffers, and the
    train step DONATES its input state — without the copy the writer
    thread would read buffers XLA has already reused for the next step
    (observed as a hard segfault in the resume test)."""
    if hasattr(x, "is_fully_addressable") and not x.is_fully_addressable:
        x = _replicate_global(x)
    return np.array(jax.device_get(x), copy=True)


# ---------------------------------------------------------------------------
# Scan / validate
# ---------------------------------------------------------------------------

_torn_announced: set[str] = set()


def _announce_torn(path: str, reason: str) -> None:
    if path in _torn_announced:
        return
    _torn_announced.add(path)
    print(
        json.dumps(
            {"event": "ckpt_torn_skipped", "dir": path, "reason": reason}
        ),
        file=sys.stderr, flush=True,
    )


def _load_manifest(ckpt_dir: str) -> dict | None:
    """Manifest of one step dir iff it validates; None (+ one structured
    stderr line) for a torn dir.  Validation = manifest parses, carries
    this format, and every leaf file exists at its recorded size — which
    the write protocol guarantees for any published dir; failure means a
    kill before publish (no manifest) or external damage."""
    path = os.path.join(ckpt_dir, "manifest.json")
    try:
        with open(path) as f:
            manifest = json.load(f)
    except FileNotFoundError:
        _announce_torn(ckpt_dir, "no manifest (write never completed)")
        return None
    except (json.JSONDecodeError, OSError) as e:
        _announce_torn(ckpt_dir, f"unreadable manifest: {e!r}")
        return None
    if manifest.get("format") != FORMAT:
        _announce_torn(ckpt_dir, f"unknown format {manifest.get('format')!r}")
        return None
    for entry in manifest.get("leaves", []):
        fpath = os.path.join(ckpt_dir, entry["file"])
        try:
            size = os.path.getsize(fpath)
        except OSError:
            _announce_torn(ckpt_dir, f"missing leaf file {entry['file']}")
            return None
        if size != entry["file_bytes"]:
            _announce_torn(
                ckpt_dir,
                f"leaf {entry['file']} is {size} bytes, manifest says "
                f"{entry['file_bytes']} (truncated?)",
            )
            return None
    return manifest


def _scan_validated(directory: str) -> list[tuple[int, str, dict]]:
    """Valid (step, dir, manifest) triples, ascending by step — ONE
    validation pass; consumers reuse the loaded manifest instead of
    re-validating (which would both re-pay the I/O and open a window
    where a dir damaged between the two reads returns None into a
    crash instead of the clean torn-skip path)."""
    out = []
    try:
        names = os.listdir(directory)
    except FileNotFoundError:
        return []
    for name in names:
        m = _STEP_DIR_RE.match(name)
        if not m:
            continue
        path = os.path.join(directory, name)
        manifest = _load_manifest(path)
        if manifest is not None:
            out.append((int(m.group(1)), path, manifest))
    return sorted(out, key=lambda t: t[0])


def scan_checkpoints(directory: str) -> list[tuple[int, str]]:
    """Valid (step, dir) pairs under ``directory``, ascending by step.
    Torn/in-progress dirs are skipped (announced once per process)."""
    return [(s, p) for s, p, _ in _scan_validated(directory)]


def latest_step(directory: str) -> int | None:
    """Latest restorable checkpointed step under ``directory``, or None."""
    ckpts = scan_checkpoints(directory)
    return ckpts[-1][0] if ckpts else None


def read_manifest(directory: str, step: int | None = None) -> dict | None:
    """The (validated) manifest of ``step`` (default: latest), or None.
    The cheap peek path — ``train.py --resume-elastic`` reads the saved
    data-order metadata from here before building the input pipeline."""
    ckpts = _scan_validated(directory)
    if not ckpts:
        return None
    if step is None:
        return ckpts[-1][2]
    for s, _, manifest in ckpts:
        if s == step:
            return manifest
    return None


# ---------------------------------------------------------------------------
# The write protocol
# ---------------------------------------------------------------------------


def _write_step_dir(
    directory: str,
    step: int,
    leaves: list[tuple[str, np.ndarray]],
    zero_world_size: int | None,
    metadata: Mapping[str, Any] | None,
) -> str:
    """Write one checkpoint with the crash-safe protocol; returns the
    published dir.  Runs in the writer thread (async) or the caller
    thread (sync escape hatch) — process 0 only."""
    final = os.path.join(directory, f"ckpt-{step}")
    tmp = os.path.join(directory, f"{_TMP_PREFIX}{step}-{os.getpid()}")
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    entries = []
    mid = max(1, len(leaves) // 2)
    for i, (path, arr) in enumerate(leaves):
        if i == mid:
            # One deterministic mid-write chaos point per save (a torn
            # half-written dir is the state this phase must leave safe).
            _chaos_point("tmp_write")
        fname = f"leaf_{i:05d}.npy"
        fpath = os.path.join(tmp, fname)
        with open(fpath, "wb") as f:
            np.save(f, arr)
            f.flush()
            os.fsync(f.fileno())
        entries.append(
            {
                "path": path,
                "file": fname,
                "shape": list(arr.shape),
                "dtype": str(arr.dtype),
                "file_bytes": os.path.getsize(fpath),
                "crc32": zlib.crc32(np.ascontiguousarray(arr).data) & 0xFFFFFFFF,
            }
        )
    _chaos_point("manifest_commit")
    # The manifest is the commit record: written + fsync'd LAST, inside
    # the tmp dir, so no published dir can exist without one and no dir
    # with one can lack its bytes.
    atomic_write_json(
        os.path.join(tmp, "manifest.json"),
        {
            "format": FORMAT,
            "version": FORMAT_VERSION,
            "step": step,
            "zero_world_size": zero_world_size,
            "metadata": dict(metadata or {}),
            "leaves": entries,
        },
        indent=1,
    )
    _chaos_point("rename")
    if os.path.exists(final):
        # A re-save of an already-PUBLISHED step (the epilogue's force
        # save after an interval save, a healed run re-reaching its
        # restore step).  If the existing dir validates, keep it and
        # drop ours: deleting a valid checkpoint before the rename would
        # open a kill window with NEITHER copy on disk — the exact
        # protocol violation this module exists to rule out.  Only a
        # TORN existing dir (which holds nothing restorable) is removed.
        if _load_manifest(final) is not None:
            shutil.rmtree(tmp, ignore_errors=True)
            return final
        shutil.rmtree(final)
    os.rename(tmp, final)
    _chaos_point("finalize")
    fsync_dir(directory)
    return final


def _gc(directory: str, max_to_keep: int) -> None:
    """Drop checkpoints beyond ``max_to_keep`` and stale tmp dirs (a
    previous process's interrupted writes; OUR tmp was just renamed)."""
    ckpts = scan_checkpoints(directory)
    for _, path in ckpts[:-max_to_keep] if max_to_keep > 0 else []:
        shutil.rmtree(path, ignore_errors=True)
    for name in os.listdir(directory):
        if name.startswith(_TMP_PREFIX):
            shutil.rmtree(os.path.join(directory, name), ignore_errors=True)


# ---------------------------------------------------------------------------
# The manager
# ---------------------------------------------------------------------------


class CheckpointManager:
    """Crash-safe (async by default) TrainState checkpointing.

    API-compatible with the orbax-era manager (save/should_save/restore/
    restore_arrays/latest_step/wait/close) plus:

    - ``metadata``: dict recorded in every manifest (train.py stores the
      data-order facts ``--resume-elastic`` re-derives from);
    - ``sink``: optional EventSink — the writer emits one structured
      ``ckpt_saved`` event per landed checkpoint (step, write seconds,
      bytes), the artifact CKPTBENCH and the RUNBOOK triage read;
    - ``restore()`` is world-size-elastic for the optimizer state (see
      module docstring) and returns HOST numpy leaves — placement onto a
      mesh is the caller's job (run_training's replication block).
    """

    def __init__(
        self,
        directory: str,
        max_to_keep: int = 3,
        save_interval_steps: int = 1,
        metadata: Mapping[str, Any] | None = None,
        sink: Any | None = None,
        async_save: bool | None = None,
    ):
        self._directory = directory
        self._max_to_keep = max_to_keep
        self._interval = max(1, int(save_interval_steps))
        self._metadata = dict(metadata or {})
        self._sink = sink
        self._async = _async_default() if async_save is None else async_save
        self._is_writer = jax.process_index() == 0
        if self._is_writer:
            os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()
        self._error: BaseException | None = None
        self._last_queued: int | None = latest_step(directory)
        # Writer thread state (started lazily on the first async save).
        self._work: queue.Queue = queue.Queue(maxsize=2)
        self._inflight = threading.Event()
        self._done = threading.Condition()
        self._thread: threading.Thread | None = None
        self._closed = False

    @property
    def directory(self) -> str:
        return self._directory

    # ---- save ------------------------------------------------------------

    def should_save(self, step: int) -> bool:
        """Would ``save(step)`` actually write (interval/dedup policy)?

        Lets the training loop run pre-save checks (the non-finite-loss
        gate) only when a save is really about to happen.  Pure host
        arithmetic — no disk scan (the latest step is tracked in-memory).
        """
        if step == self._last_queued:
            return False
        return step % self._interval == 0

    def save(
        self,
        state: TrainState,
        step: int | None = None,
        force: bool = False,
        metadata: Mapping[str, Any] | None = None,
    ) -> bool:
        """Snapshot ``state`` and (async) write checkpoint ``step``.

        The snapshot happens HERE, synchronously — under the caller's step
        serialization, so it is exactly the state at ``step`` — then the
        write overlaps whatever the caller does next.  One-behind: a save
        issued while the previous write is still in flight first waits for
        it (bounded by that write's own duration), so host memory holds at
        most one pending checkpoint.  A failed previous write re-raises
        here (the crash channel).
        """
        if self._closed:
            raise RuntimeError("CheckpointManager is closed")
        step = int(jax.device_get(state.step)) if step is None else int(step)
        if not force and not self.should_save(step):
            return False
        self._join_inflight()  # one-behind + surfaces writer errors
        self._raise_pending_error()
        zero_world = _detect_zero_world(state.opt_state)
        flat = _flatten_with_paths(_saveable(state))
        with trace.span("ckpt_snapshot", step=step):
            if self._is_writer:
                leaves = [(path, _host_leaf(leaf)) for path, leaf in flat]
            else:
                # Non-writers only owe the COLLECTIVE half: join the
                # gather for cross-host sharded leaves so process 0 can
                # read the full value.  No device→host copy of the rest
                # — that would burn full-model D2H bandwidth and a
                # checkpoint-sized host allocation on N-1 hosts for
                # bytes nobody writes.
                for _, leaf in flat:
                    if (
                        hasattr(leaf, "is_fully_addressable")
                        and not leaf.is_fully_addressable
                    ):
                        _replicate_global(leaf)
                leaves = []
        _chaos_point("snapshot")
        self._last_queued = step
        if not self._is_writer:
            return True  # participated in the gather; process 0 writes
        meta = dict(self._metadata)
        if metadata:
            meta.update(metadata)
        if not self._async:
            self._write_one(step, leaves, zero_world, meta)
            self._raise_pending_error()
            return True
        self._ensure_thread()
        self._inflight.set()
        telemetry.record_ckpt_inflight(1)
        self._work.put((step, leaves, zero_world, meta))
        return True

    def _write_one(
        self,
        step: int,
        leaves: list[tuple[str, np.ndarray]],
        zero_world: int | None,
        meta: dict,
    ) -> None:
        t0 = monotonic_s()
        try:
            with trace.span("ckpt_write", step=step):
                _write_step_dir(
                    self._directory, step, leaves, zero_world, meta
                )
                _gc(self._directory, self._max_to_keep)
        except BaseException as e:
            with self._lock:
                self._error = e
            # Crash channel: announce NOW (the loop may be minutes from
            # its next save), re-raise at the next save()/wait()/close().
            print(
                json.dumps(
                    {"event": "ckpt_write_error", "step": step,
                     "error": repr(e)[:500]}
                ),
                file=sys.stderr, flush=True,
            )
        else:
            dt = monotonic_s() - t0
            total_bytes = sum(arr.nbytes for _, arr in leaves)
            telemetry.record_ckpt_save(step, dt, total_bytes)
            event = getattr(self._sink, "event", None)
            if event is not None:
                try:
                    event(
                        "ckpt_saved", step=step, write_s=round(dt, 4),
                        bytes=total_bytes,
                    )
                except Exception:
                    pass  # a broken sink must not fail the save

    # ---- the writer thread ----------------------------------------------

    def _ensure_thread(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        hb = watchdog.register("ckpt-writer")
        hb.idle()

        def run() -> None:
            try:
                while True:
                    item = self._work.get()
                    if item is None:
                        return
                    hb.beat()
                    self._write_one(*item)
                    hb.idle()
                    telemetry.record_ckpt_inflight(0)
                    self._inflight.clear()
                    with self._done:
                        self._done.notify_all()
            except BaseException as e:  # never die silently (error contract)
                with self._lock:
                    if self._error is None:
                        self._error = e
                telemetry.record_ckpt_inflight(0)
                self._inflight.clear()
                with self._done:
                    self._done.notify_all()
                print(
                    json.dumps(
                        {"event": "ckpt_writer_crashed",
                         "error": repr(e)[:500]}
                    ),
                    file=sys.stderr, flush=True,
                )
                raise
            finally:
                hb.close()

        # watchdog: hb registered above (ckpt-writer); beats per write,
        # idle between saves, closed in run()'s finally.
        self._thread = threading.Thread(
            target=run, daemon=True, name="ckpt-writer"
        )
        self._thread.start()

    def _join_inflight(self) -> None:
        while self._inflight.is_set():
            with self._done:
                self._done.wait(timeout=0.5)

    def _raise_pending_error(self) -> None:
        with self._lock:
            error, self._error = self._error, None
        if error is not None:
            raise RuntimeError(
                "checkpoint write failed (root cause chained)"
            ) from error

    def wait(self) -> None:
        """Block until in-flight saves land; re-raise a failed write."""
        self._join_inflight()
        self._raise_pending_error()

    def close(self) -> None:
        if self._closed:
            return
        self._join_inflight()
        if self._thread is not None and self._thread.is_alive():
            self._work.put(None)
            self._thread.join(timeout=30)
        self._closed = True
        self._raise_pending_error()

    # ---- restore ---------------------------------------------------------

    def latest_step(self) -> int | None:
        return latest_step(self._directory)

    def _target(self, step: int | None) -> tuple[int, str, dict]:
        ckpts = _scan_validated(self._directory)
        if step is not None:
            for s, path, manifest in ckpts:
                if s == step:
                    return s, path, manifest
            raise FileNotFoundError(
                f"no restorable checkpoint for step {step} in "
                f"{self._directory}"
            )
        if not ckpts:
            raise FileNotFoundError(
                f"no checkpoint in {self._directory}"
            )
        return ckpts[-1]

    def restore(self, state: TrainState, step: int | None = None) -> TrainState:
        """Restore into the structure of ``state`` (the shapes template).

        ``state`` must be a freshly-initialized TrainState for the same
        model and optimizer — but NOT necessarily the same world layout:
        optimizer-state leaves are re-laid into the template's layout
        (``reshard_flat_leaf``), so a ZeRO checkpoint from world N
        restores into a world-M template or a replicated one, and vice
        versa.  Returns host numpy leaves; the caller places them (the
        loop's replication block / an explicit device_put).
        """
        _, ckpt_dir, manifest = self._target(step)
        saved = self._load_leaves(ckpt_dir, manifest)
        template = _saveable(state)
        flat, treedef = jax.tree_util.tree_flatten_with_path(template)
        t_paths = [jax.tree_util.keystr(p) for p, _ in flat]
        is_comm = lambda p: p.startswith("['comm_state']")  # noqa: E731
        missing = [p for p in t_paths if p not in saved]
        extra = [p for p in saved if p not in set(t_paths)]
        # Comm EF residuals (ISSUE 13) are ADVISORY state: a template/
        # checkpoint mismatch there (compression newly enabled, mode or
        # bucket layout changed, or a ZeRO<->DP switch re-keying the
        # residuals) must not refuse a restore — the residuals reset to
        # zero (EF re-converges in a handful of steps) and exactly ONE
        # structured ef_reset event says so.  Params/optimizer mismatch
        # still refuses loudly below.
        comm_missing = {p for p in missing if is_comm(p)}
        comm_dropped = [p for p in extra if is_comm(p)]
        missing = [p for p in missing if not is_comm(p)]
        extra = [p for p in extra if not is_comm(p)]
        if missing or extra:
            raise ValueError(
                f"checkpoint {ckpt_dir} does not match this model/"
                f"optimizer: missing leaves {missing[:5]}"
                f"{'...' if len(missing) > 5 else ''}, unexpected leaves "
                f"{extra[:5]}{'...' if len(extra) > 5 else ''}"
            )
        comm_reset = sorted(comm_missing)
        out = []
        for path, leaf in zip(t_paths, (l for _, l in flat)):
            shape = tuple(int(d) for d in np.shape(leaf))
            dtype = np.dtype(getattr(leaf, "dtype", np.asarray(leaf).dtype))
            if path in comm_missing:
                out.append(np.zeros(shape, dtype))
                continue
            arr = saved[path]
            if is_comm(path):
                # Comm residuals reshard like ZeRO slots (same flat
                # padding-is-zeros layout) — but they are ADVISORY: a
                # re-lay that would drop real content (bucket size
                # changed under the same key) zeroes the leaf instead
                # of refusing the restore, counted into the single
                # ef_reset record below.
                try:
                    out.append(reshard_flat_leaf(arr, shape, dtype, path))
                except ValueError:
                    out.append(np.zeros(shape, dtype))
                    comm_reset.append(path)
                continue
            if path.startswith("['opt_state']"):
                # Flat ZeRO-layout optimizer slots re-lay into the
                # template's world size; dropping real data REFUSES.
                out.append(reshard_flat_leaf(arr, shape, dtype, path))
                continue
            if arr.shape != shape or arr.dtype != dtype:
                raise ValueError(
                    f"checkpoint leaf {path}: saved {arr.shape}/{arr.dtype}"
                    f" != expected {shape}/{dtype} — a different model was "
                    "checkpointed here"
                )
            out.append(arr)
        if comm_reset or comm_dropped:
            self._announce_ef_reset(ckpt_dir, comm_reset, comm_dropped)
        restored = jax.tree_util.tree_unflatten(treedef, out)
        return dataclasses.replace(
            state,
            step=restored["step"],
            params=restored["params"],
            batch_stats=restored["batch_stats"],
            opt_state=restored["opt_state"],
            comm_state=restored["comm_state"],
        )

    def _announce_ef_reset(
        self, ckpt_dir: str, zeroed: list, dropped: list
    ) -> None:
        """ONE structured ef_reset record per restore: the EF residual
        state could not be carried over (see restore()) and was zeroed/
        dropped — visible in metrics.jsonl (sink) and on stderr."""
        payload = {
            "event": "ef_reset",
            "dir": ckpt_dir,
            "zeroed": len(zeroed),
            "dropped": len(dropped),
            "reason": (
                "checkpoint comm_state does not match this run's comm "
                "policy/layout; error-feedback residuals reset to zero "
                "(EF re-converges within a few steps)"
            ),
        }
        print(json.dumps(payload), file=sys.stderr, flush=True)
        event = getattr(self._sink, "event", None)
        if event is not None:
            try:
                fields = {k: v for k, v in payload.items() if k != "event"}
                event("ef_reset", **fields)
            except Exception:
                pass  # a broken sink must not fail the restore

    def restore_arrays(self, step: int | None = None) -> dict[str, Any]:
        """The saved tree as nested host dicts, no template needed.

        For consumers that must not depend on the optimizer that produced
        the snapshot — the export path (convert_model.py) keeps only
        params/batch_stats/step.  ``opt_state`` leaves are returned under
        a FLAT ``{keypath: array}`` dict (their pytree structure needs the
        optimizer to rebuild; no template-free consumer wants them).
        """
        _, ckpt_dir, manifest = self._target(step)
        saved = self._load_leaves(ckpt_dir, manifest)
        out: dict[str, Any] = {"opt_state": {}}
        key_re = re.compile(r"\['([^']*)'\]")
        for path, arr in saved.items():
            if path.startswith("['opt_state']"):
                out["opt_state"][path] = arr
                continue
            keys = key_re.findall(path)
            if path == "['step']":
                out["step"] = arr
                continue
            node = out
            for k in keys[:-1]:
                node = node.setdefault(k, {})
            node[keys[-1]] = arr
        out.setdefault("params", {})
        out.setdefault("batch_stats", {})
        return out

    @staticmethod
    def _load_leaves(ckpt_dir: str, manifest: dict) -> dict[str, np.ndarray]:
        verify = os.environ.get("RETINANET_CKPT_VERIFY", "0").lower() in (
            "1", "true",
        )
        out = {}
        for entry in manifest["leaves"]:
            arr = np.load(os.path.join(ckpt_dir, entry["file"]))
            if verify:
                crc = zlib.crc32(np.ascontiguousarray(arr).data) & 0xFFFFFFFF
                if crc != entry["crc32"]:
                    raise ValueError(
                        f"checkpoint leaf {entry['path']} in {ckpt_dir} "
                        f"fails its crc32 (bit rot / external damage)"
                    )
            out[entry["path"]] = arr
        return out
