"""Checkpoint/resume on top of orbax.

Reference behavior being replaced (SURVEY.md §5.4): Keras ``ModelCheckpoint``
on rank 0 wrote one full-model ``.h5`` per epoch WITHOUT optimizer state, so
resume restarted the optimizer; a separate ``convert_model.py`` produced the
inference snapshot.  Here the FULL train state (params + batch_stats +
optimizer state + step) is saved via orbax — async, multi-host-aware (every
process participates in the save of its addressable shards; orbax handles
coordination) — and resume is bit-exact.  No conversion step exists because
inference is just another jitted function over the same params
(evaluate/detect.py).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any

import jax
import orbax.checkpoint as ocp

from batchai_retinanet_horovod_coco_tpu.train.state import TrainState

# Async checkpointing is the production default (the save overlaps the next
# train steps).  RETINANET_ASYNC_CKPT=0 forces the synchronous path: orbax's
# async finalize thread (asyncio loop woken cross-thread + grpc) segfaults
# under sandboxed kernels (gVisor dev boxes) when saves land back-to-back —
# observed deterministically in test_loop's checkpoint_every=1 resume test —
# so the test env opts out (tests/conftest.py).
_ASYNC_CKPT = os.environ.get("RETINANET_ASYNC_CKPT", "1").lower() not in (
    "0", "false",
)


def _saveable(state: TrainState) -> dict[str, Any]:
    """The pytree that goes to disk (drops the static optax transform)."""
    return {
        "step": state.step,
        "params": state.params,
        "batch_stats": state.batch_stats,
        "opt_state": state.opt_state,
    }


class CheckpointManager:
    """Thin wrapper over ``ocp.CheckpointManager`` for TrainState pytrees."""

    def __init__(
        self,
        directory: str,
        max_to_keep: int = 3,
        save_interval_steps: int = 1,
    ):
        self._mgr = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                create=True,
                enable_async_checkpointing=_ASYNC_CKPT,
            ),
        )

    @property
    def directory(self) -> str:
        return str(self._mgr.directory)

    def save(
        self, state: TrainState, step: int | None = None, force: bool = False
    ) -> bool:
        """Async-save at ``step`` (default: ``state.step``, which costs a
        device sync — pass the host-tracked step in hot loops)."""
        return self._mgr.save(
            int(state.step) if step is None else step,
            args=ocp.args.StandardSave(_saveable(state)),
            force=force,
        )

    def should_save(self, step: int) -> bool:
        """Would ``save(step)`` actually write (interval/dedup policy)?

        Lets the training loop run pre-save checks (e.g. the non-finite-loss
        abort) only when a save is really about to happen, instead of paying
        a device sync every step.
        """
        return self._mgr.should_save(step)

    def restore(self, state: TrainState, step: int | None = None) -> TrainState:
        """Restore into the structure of ``state`` (shapes/shardings template).

        ``state`` must be a freshly-initialized TrainState for the same model
        and optimizer; returns it with restored values and step.
        """
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {self.directory}")
        template = jax.tree.map(
            lambda x: ocp.utils.to_shape_dtype_struct(x), _saveable(state)
        )
        restored = self._mgr.restore(
            step, args=ocp.args.StandardRestore(template)
        )
        return dataclasses.replace(
            state,
            step=restored["step"],
            params=restored["params"],
            batch_stats=restored["batch_stats"],
            opt_state=restored["opt_state"],
        )

    def restore_arrays(self, step: int | None = None) -> dict[str, Any]:
        """Restore the COMPLETE saved tree without a caller-supplied template.

        For consumers that must not depend on the optimizer that produced
        the snapshot — the export path (convert_model.py) keeps only
        params/batch_stats/step, the inference analogue of the reference
        loading a training ``.h5`` without recompiling its optimizer.

        Note: the whole tree, opt_state included, is materialized (orbax
        rejects partial-structure templates and ``item_metadata`` is not
        available under this manager configuration), so this costs one full
        checkpoint read; callers discard what they don't need.
        """
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {self.directory}")
        return self._mgr.restore(step, args=ocp.args.StandardRestore())

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def wait(self) -> None:
        """Block until in-flight async saves land (call before process exit)."""
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self.wait()
        self._mgr.close()


def latest_step(directory: str) -> int | None:
    """Latest checkpointed step under ``directory``, or None."""
    with ocp.CheckpointManager(directory) as mgr:
        return mgr.latest_step()
