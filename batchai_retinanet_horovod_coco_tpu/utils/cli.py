"""Shared CLI glue for anchor hyperparameters (train.py / convert_model.py /
debug.py).

keras-retinanet carried custom anchor parameters in a ``--config`` ini and
baked them into the saved model (SURVEY.md M5/M11); here the equivalent is a
single flag surface (``add_anchor_flags``) plus a JSON sidecar persisted next
to the checkpoint (``save_anchor_config``), so eval/export/debug can never
silently regenerate default anchors for a model trained with custom ones —
anchors parameterize box decoding, so a mismatch produces garbage detections
with no error anywhere.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os

from batchai_retinanet_horovod_coco_tpu.ops.anchors import AnchorConfig

_ANCHOR_FILE = "anchor_config.json"
_FLAG_FIELDS = ("sizes", "strides", "ratios", "scales")


def float_list(text: str) -> tuple[float, ...]:
    """argparse type for comma-separated floats ('32,64' → (32.0, 64.0))."""
    try:
        values = tuple(float(v) for v in text.split(",") if v.strip())
    except ValueError:
        raise argparse.ArgumentTypeError(f"not a float list: {text!r}")
    if not values:
        raise argparse.ArgumentTypeError("empty list")
    return values


def add_anchor_flags(parser) -> None:
    """The anchor flag surface, identical on every tool that builds anchors."""
    parser.add_argument("--anchor-sizes", type=float_list, default=None,
                        metavar="S3,S4,S5,S6,S7",
                        help="anchor base size per pyramid level "
                             "(default 32,64,128,256,512)")
    parser.add_argument("--anchor-strides", type=float_list, default=None,
                        metavar="T3,T4,T5,T6,T7",
                        help="anchor stride per pyramid level "
                             "(default 8,16,32,64,128)")
    parser.add_argument("--anchor-ratios", type=float_list, default=None,
                        help="aspect ratios (default 0.5,1,2)")
    parser.add_argument("--anchor-scales", type=float_list, default=None,
                        help="octave scales (default 1,2^(1/3),2^(2/3))")


def add_data_pipeline_flags(parser) -> None:
    """The host input-pipeline flag surface (train.py; one definition so
    tools that grow a pipeline later can't drift).

    Sizing guidance lives in RUNBOOK.md ("Feeding the chips"): threads
    plateau at ~2 effective workers (PIL JPEG decode holds the GIL), so on
    hosts that must exceed ~35 imgs/s the process pool is the lever.
    """
    parser.add_argument("--workers", "--data-workers", dest="workers",
                        type=int, default=16,
                        help="decode THREADS for the in-process pool "
                             "(ignored when --data-worker-procs > 0 "
                             "selects the multiprocess producer)")
    parser.add_argument("--data-worker-procs", type=int, default=0,
                        help="decode worker PROCESSES writing into shared-"
                             "memory ring buffers (data/shm_pipeline.py); "
                             "0 = in-process thread pool.  Use ~1 process "
                             "per 35 imgs/s of step demand; bit-identical "
                             "batches either way")
    parser.add_argument("--data-worker-timeout", type=float, default=120.0,
                        help="seconds a head-of-line batch may stall before "
                             "the multiprocess pipeline raises (crash "
                             "detection is immediate; this bounds WEDGED "
                             "workers)")
    parser.add_argument("--device-prefetch", type=int, default=2,
                        help="batches transferred host->device ahead of the "
                             "step by a background thread (double "
                             "buffering); 0 = synchronous transfer")


def add_comm_flags(parser) -> None:
    """The gradient-communication flag surface (ISSUE 13, train.py).

    One definition so the chaos harness, COMMBENCH sweep, and any future
    tool that grows a compressed collective expose identical knobs.
    ``--quantized-allreduce`` (train.py) survives as a deprecated alias
    that maps onto ``--comm-compress int8`` with one structured
    deprecation warning (``make_comm_config``)."""
    parser.add_argument("--comm-compress", default="none",
                        choices=["none", "int8", "bf16"],
                        help="gradient-compression wire format "
                             "(comm/compress.py): int8 = bucketed "
                             "per-block symmetric int8 with error "
                             "feedback (~5/8 the exact bytes-on-wire), "
                             "bf16 = round-to-nearest bf16 (~3/4); the "
                             "reduce phase stays exact f32 either way.  "
                             "Composes with --shard-weight-update (the "
                             "compression moves to the ZeRO update "
                             "gather).  none = byte-identical "
                             "pre-ISSUE-13 step")
    parser.add_argument("--comm-overlap", action="store_true",
                        help="issue each schedule stage's (backbone/fpn/"
                             "heads) compressed collective from INSIDE "
                             "the backward pass (comm/overlap.py "
                             "custom-vjp staging) instead of one fused "
                             "pass after it; identical values, earlier "
                             "wire time.  DP path only: with "
                             "--shard-weight-update the compression is "
                             "the post-update gather and this flag is "
                             "ignored with a structured warning")
    parser.add_argument("--comm-bucket-mb", type=float, default=4.0,
                        help="bucket capacity in MB: leaves pack per "
                             "stage into flat buckets of this size so "
                             "small leaves share one quantized "
                             "collective; a bucket under "
                             "min_bucket_bytes stays exact")
    parser.add_argument("--comm-no-error-feedback", action="store_true",
                        help="disable the error-feedback residual "
                             "(comm state): quantization error is then "
                             "dropped each step instead of carried — "
                             "debugging/ablation only")
    # Topology-aware hierarchical tree (ISSUE 16): per-hop policy +
    # the slice-count knob that activates it.
    parser.add_argument("--comm-slices", type=int, default=None,
                        metavar="N",
                        help="slice count of the two-level device "
                             "grouping (parallel/mesh.py CommTopology): "
                             "with N > 1 and distinct per-hop modes the "
                             "gradient collective becomes hierarchical "
                             "— exact f32 within each ICI slice, "
                             "compressed exchange only on the "
                             "cross-slice DCN hop.  Default: derived "
                             "from the devices' slice_index (real "
                             "multi-slice TPU) or the "
                             "RETINANET_COMM_SLICES env; on the "
                             "virtual CPU mesh pass e.g. 2 to emulate "
                             "2 slices x 4 devices")
    parser.add_argument("--comm-ici-mode", default=None,
                        choices=["none", "int8", "bf16"],
                        help="wire format of the intra-slice (ICI) "
                             "hops once a topology engages; default "
                             "none = the fast wire stays exact f32.  "
                             "A compressed ici mode must equal the dcn "
                             "mode (which is just the flat tree)")
    parser.add_argument("--comm-dcn-mode", default=None,
                        choices=["none", "int8", "bf16"],
                        help="wire format of the cross-slice (DCN) hop "
                             "once a topology engages; default: "
                             "inherit --comm-compress — so "
                             "'--comm-compress int8 --comm-slices 2' "
                             "alone gives exact-ICI / int8-DCN")
    parser.add_argument("--comm-dcn-bucket-mb", type=float, default=None,
                        metavar="MB",
                        help="bucket capacity for the hierarchical "
                             "plan, sized for the DCN hop (the wire "
                             "that actually hurts); default: inherit "
                             "--comm-bucket-mb")


def make_comm_config(args):
    """CommConfig (or None) from the flags above + the deprecated
    ``--quantized-allreduce`` alias.  The alias maps onto the comm
    subsystem with ONE structured deprecation warning on stderr — the
    behavior change (bucketed + EF instead of per-leaf, no EF) is
    announced, never silent."""
    import json as _json
    import sys as _sys

    from batchai_retinanet_horovod_coco_tpu.comm import CommConfig

    compress = getattr(args, "comm_compress", "none") or "none"
    if getattr(args, "quantized_allreduce", False):
        if compress == "none":
            compress = "int8"
        print(
            _json.dumps({
                "event": "deprecated_flag",
                "flag": "--quantized-allreduce",
                "mapped_to": f"--comm-compress {compress}",
                "note": (
                    "the per-leaf quantized allreduce was subsumed by "
                    "the comm/ subsystem (bucketed, error-feedback; "
                    "ISSUE 13) — switch to --comm-compress"
                ),
            }),
            file=_sys.stderr, flush=True,
        )
    overlap = bool(getattr(args, "comm_overlap", False))
    ici_mode = getattr(args, "comm_ici_mode", None)
    dcn_mode = getattr(args, "comm_dcn_mode", None)
    dcn_bucket_mb = getattr(args, "comm_dcn_bucket_mb", None)
    if (
        compress == "none"
        and not overlap
        and (dcn_mode or "none") == "none"
        and (ici_mode or "none") == "none"
    ):
        return None
    return CommConfig(
        compress=compress,
        overlap=overlap,
        bucket_mb=float(getattr(args, "comm_bucket_mb", 4.0)),
        error_feedback=not getattr(args, "comm_no_error_feedback", False),
        ici_mode=ici_mode,
        dcn_mode=dcn_mode,
        dcn_bucket_mb=(
            None if dcn_bucket_mb is None else float(dcn_bucket_mb)
        ),
    )


def add_obs_flags(parser) -> None:
    """The observability flag surface (train.py / evaluate.py; ISSUE 3).

    One definition so every tool that grows tracing exposes the same
    knobs.  With both flags off the subsystem costs nothing: spans check
    one module-level bool and heartbeats are attribute stores."""
    parser.add_argument("--obs-trace", action="store_true",
                        help="record trace spans (step loop, data "
                             "pipeline, shm decode workers, prefetch, "
                             "eval consumer) and export a Perfetto-"
                             "loadable Chrome trace JSON into --obs-dir "
                             "at exit (obs/trace.py)")
    parser.add_argument("--obs-dir", default=None,
                        help="observability artifact directory (trace "
                             "JSON, watchdog stack dumps); default "
                             "artifacts/obs when --obs-trace is set")
    parser.add_argument("--obs-stall-timeout", type=float, default=120.0,
                        help="seconds a registered component may go "
                             "without a heartbeat before the watchdog "
                             "dumps a stall diagnosis (structured JSON + "
                             "all-thread stacks; it never kills the run "
                             "— obs/watchdog.py).  Only takes effect "
                             "with --obs-trace/--obs-dir (the subsystem "
                             "is otherwise fully disabled)")
    # Live telemetry + SLO surface (ISSUE 9, obs/telemetry.py + obs/slo.py)
    parser.add_argument("--obs-port", type=int, default=None, metavar="PORT",
                        help="start a drain-safe stdlib HTTP status "
                             "server on this port (0 = ephemeral, "
                             "printed at startup) exposing the live "
                             "telemetry registry during the run: GET "
                             "/metrics (Prometheus text exposition), "
                             "/healthz (watchdog-backed liveness — 503 "
                             "names the stalled component), /statusz "
                             "(JSON snapshot).  Read-only; daemon "
                             "threads — it can never wedge a pod exit")
    parser.add_argument("--slo-rule", action="append", default=None,
                        metavar="METRIC{>,<}THR[@FOR_S]",
                        help="declarative SLO over a telemetry snapshot "
                             "metric, evaluated by a monitor thread; a "
                             "sustained breach emits exactly ONE "
                             "structured slo_violation event (JSONL + "
                             "trace instant + PERF_REPORT violations "
                             "section).  THR 'x1.5' means regression vs "
                             "a rolling-median baseline.  Examples: "
                             "'serve_request_latency_ms.p99>250@30', "
                             "'train_step_time_ms>x1.5@60'.  Repeatable; "
                             "a watchdog-stall rule is always included")
    parser.add_argument("--slo-poll-s", type=float, default=5.0,
                        help="SLO monitor poll interval (seconds)")
    # Numerics flight recorder (ISSUE 10, obs/numerics.py)
    parser.add_argument("--numerics", action="store_true",
                        help="fuse the in-step numerics summary into the "
                             "compiled train step: pre-clip global + "
                             "per-layer-group gradient norms, update/"
                             "param ratio, non-finite count, and the "
                             "cross-replica agreement probe on mesh "
                             "runs (~2 extra global reduces per step; "
                             "the summary lands in metrics.jsonl as "
                             "structured 'numerics' records, in the "
                             "telemetry gauges the built-in nonfinite/"
                             "grad-norm-spike SLO rules watch, and in "
                             "PERF_REPORT's numerics section).  The "
                             "NaN-provenance NUMERICS_DUMP.json on a "
                             "tripped finite-check is always armed, "
                             "with or without this flag")


def add_durability_flags(parser) -> None:
    """The preemption/recovery flag surface (ISSUE 11, train.py).  One
    definition so the chaos harness (scripts/chaos.py) and any future
    tool that grows resume semantics expose identical knobs."""
    parser.add_argument("--resume-elastic", action="store_true",
                        help="on resume, re-derive the input-stream "
                             "position from the checkpoint manifest "
                             "(consumed batches = restored step) so no "
                             "batch is replayed or skipped — including "
                             "when the world size changed since the save "
                             "(the ZeRO optimizer state reshards "
                             "automatically; utils/checkpoint.py).  "
                             "Requires the same --batch-size and --seed "
                             "the checkpoint was written with (validated "
                             "against the manifest)")
    parser.add_argument("--auto-resume", action="store_true",
                        help="self-healing numerics resume: on a "
                             "non-finite abort, restore the last healthy "
                             "checkpoint (the pre-save gate guarantees "
                             "finiteness), reseed the data order and "
                             "exclude the poison batch's image ids "
                             "recorded in NUMERICS_DUMP.json, emit one "
                             "structured auto_resume event, and continue "
                             "to --steps")
    parser.add_argument("--max-auto-resumes", type=int, default=3,
                        help="give up (re-raise the abort) after this "
                             "many auto-resumes in one invocation")
    parser.add_argument("--inject-nan-step", type=int, default=None,
                        metavar="N",
                        help="FAULT INJECTION (scripts/chaos.py): poison "
                             "the N-th training batch with NaN, once per "
                             "process — exercises the numerics abort + "
                             "--auto-resume path end-to-end on a real "
                             "run.  Never use outside chaos testing")


def add_serve_flags(parser) -> None:
    """The inference-server flag surface (serve/frontend.py CLI and
    ``bench.py --mode serve``; ISSUE 4).  One definition so the bench's
    load generator and the real server can never drift on knob names."""
    parser.add_argument("--serve-max-delay-ms", type=float, default=10.0,
                        help="dynamic-batching deadline: a partial batch "
                             "fires at most this long after its first "
                             "request reaches the batcher (in continuous "
                             "mode the deadline is the upper bound; the "
                             "dispatch gate usually seals first)")
    parser.add_argument("--serve-batching", default="continuous",
                        choices=["continuous", "deadline"],
                        help="continuous (default): slot-pool in-flight "
                             "batching — batch N+1 assembles while N runs "
                             "and seals the instant the device is ready; "
                             "deadline: the classic deadline-only "
                             "coalescing (comparison/benchmark mode)")
    parser.add_argument("--serve-admission-queue", type=int, default=128,
                        help="bounded front-door queue; a full queue "
                             "REJECTS (sheds) instead of growing — "
                             "overload becomes explicit 503s, not "
                             "unbounded latency")
    parser.add_argument("--serve-bucket-queue", type=int, default=64,
                        help="bounded per-bucket coalescing queue (full "
                             "= shed with reason bucket_queue_full)")
    parser.add_argument("--serve-workers", type=int, default=2,
                        help="host decode/resize worker threads (the "
                             "serve router)")
    parser.add_argument("--serve-timeout-s", type=float, default=None,
                        help="default per-request deadline (expired "
                             "requests are rejected, never occupy a "
                             "batch row); unset = no deadline")
    parser.add_argument("--serve-drain-timeout-s", type=float, default=30.0,
                        help="graceful close() waits this long for "
                             "in-flight requests before rejecting the "
                             "remainder")
    parser.add_argument("--replica-id", default=None,
                        help="stable identity carried in /healthz load "
                             "fields (fleet routing / canary attribution "
                             "— ISSUE 12); default host-pid.  The fleet "
                             "CLI pins it across restarts so a breaker-"
                             "open replica is re-admitted as itself")


def make_serve_config(args):
    """ServeConfig from the flags above (lazy import: the serve package
    pulls the data/obs layers, which CLI-only callers may not need)."""
    from batchai_retinanet_horovod_coco_tpu.serve.common import ServeConfig

    return ServeConfig(
        max_delay_ms=args.serve_max_delay_ms,
        continuous=getattr(args, "serve_batching", "continuous")
        == "continuous",
        admission_queue=args.serve_admission_queue,
        bucket_queue=args.serve_bucket_queue,
        preprocess_workers=args.serve_workers,
        default_timeout_s=args.serve_timeout_s,
        drain_timeout_s=args.serve_drain_timeout_s,
    )


def configure_obs(args, process_label: str = "main", sink=None):
    """Bring up the obs subsystem from the flags above; returns the obs
    dir (None = disabled).  Call BEFORE building pipelines so spawned shm
    workers inherit the trace env contract."""
    if not (getattr(args, "obs_trace", False) or getattr(args, "obs_dir", None)):
        return None
    from batchai_retinanet_horovod_coco_tpu import obs

    return obs.enable(
        args.obs_dir or "artifacts/obs",
        process_label=process_label,
        stall_after=getattr(args, "obs_stall_timeout", 120.0),
        sink=sink,
    )


def make_pipeline_worker_kwargs(args) -> dict:
    """PipelineConfig kwargs for the worker/prefetch flags above."""
    return dict(
        num_workers=args.workers,
        num_worker_procs=getattr(args, "data_worker_procs", 0) or 0,
        worker_timeout=getattr(args, "data_worker_timeout", 120.0),
    )


def make_anchor_config(args) -> AnchorConfig:
    """AnchorConfig from the CLI flags (defaults where flags are unset).

    One config object threads through the model (head sizing), the train
    step, detection, and export so they can never disagree.
    """
    default = AnchorConfig()
    kw = {}
    if args.anchor_sizes is not None:
        kw["sizes"] = args.anchor_sizes
    if args.anchor_strides is not None:
        for s in args.anchor_strides:
            if not float(s).is_integer():
                raise SystemExit(
                    f"--anchor-strides must be whole numbers, got {s}"
                )
        kw["strides"] = tuple(int(s) for s in args.anchor_strides)
    if args.anchor_ratios is not None:
        kw["ratios"] = args.anchor_ratios
    if args.anchor_scales is not None:
        kw["scales"] = args.anchor_scales
    for key in ("sizes", "strides"):
        if key in kw and len(kw[key]) != len(default.levels):
            raise SystemExit(
                f"--anchor-{key} needs {len(default.levels)} entries "
                f"(one per pyramid level {default.levels}), got {len(kw[key])}"
            )
    return dataclasses.replace(default, **kw) if kw else default


def save_anchor_config(snapshot_dir: str, config: AnchorConfig) -> None:
    """Persist the anchor config next to the checkpoints (process 0 only).

    Atomic (temp file + rename) and skipped when unchanged: peer processes
    read this file at startup with no barrier in between, so a truncating
    rewrite could be observed half-written.
    """
    os.makedirs(snapshot_dir, exist_ok=True)
    if load_anchor_config(snapshot_dir) == config:
        return
    path = os.path.join(snapshot_dir, _ANCHOR_FILE)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(dataclasses.asdict(config), f, indent=1)
    os.replace(tmp, path)


def load_anchor_config(snapshot_dir: str | None) -> AnchorConfig | None:
    if not snapshot_dir:
        return None
    path = os.path.join(snapshot_dir, _ANCHOR_FILE)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        raw = json.load(f)
    return AnchorConfig(**{k: tuple(v) for k, v in raw.items()})


def resolve_anchor_config(
    args, snapshot_dir: str | None, fresh: bool = False
) -> AnchorConfig:
    """Combine CLI flags with the config persisted beside the checkpoint.

    - flags given, no saved config (or they match): use the flags;
    - no flags, saved config present: use the saved one (an eval/export/
      resume run never has to repeat the flags);
    - both present and DIFFERENT: abort — mixing anchors across a
      checkpoint boundary decodes garbage, never do it silently.
    - ``fresh`` (--no-resume): the run deliberately ignores prior state,
      so the flags (or defaults) win and the stale sidecar is ignored
      (the caller's save then overwrites it).
    """
    from_flags = make_anchor_config(args)
    if fresh:
        return from_flags
    flags_given = any(
        getattr(args, f"anchor_{k}") is not None for k in _FLAG_FIELDS
    )
    saved = load_anchor_config(snapshot_dir)
    if saved is None:
        return from_flags
    if not flags_given:
        if saved != AnchorConfig():
            print(f"using anchor config persisted in {snapshot_dir}")
        return saved
    if from_flags != saved:
        raise SystemExit(
            f"anchor flags conflict with the config persisted in "
            f"{snapshot_dir} (trained with {saved}); drop the flags to use "
            "the saved config, or point --snapshot-path elsewhere"
        )
    return from_flags
