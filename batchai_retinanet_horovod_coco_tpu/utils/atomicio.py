"""Atomic artifact writes: tmp-then-rename, fsync'd (ISSUE 11).

Any file a restore/resume/consumer path SCANS — checkpoint manifests,
export manifests, schedule registries, lint baselines, trace exports —
must never be observable half-written: a reader racing a plain
``open(path, "w")`` (or a process killed mid-write) sees a truncated
file and either crashes or, worse, silently loads garbage.  The
protocol here is the standard one the checkpoint subsystem is built on
(utils/checkpoint.py):

1. write the full payload to ``<path>.tmp-<pid>`` in the SAME directory
   (``os.replace`` is only atomic within one filesystem),
2. flush + ``os.fsync`` the file so the bytes are durable before the
   name is,
3. ``os.replace`` onto the final name (atomic on POSIX),
4. optionally fsync the parent directory so the rename itself survives
   a power cut (``fsync_dir`` — the checkpoint writer does this; most
   artifact writers accept the tiny window).

The ``atomic-artifacts`` lint rule (analysis/rules/atomic_artifacts.py)
enforces the pattern package-wide: a write-mode ``open`` in a function
with no rename is a finding unless it goes through these helpers.

stdlib-only — importable from jax-free processes (shm decode workers,
the analysis package, obs.trace).
"""

from __future__ import annotations

import contextlib
import json
import os
from typing import Any, Iterator


def _tmp_path(path: str) -> str:
    head, tail = os.path.split(path)
    return os.path.join(head, f".{tail}.tmp-{os.getpid()}")


def atomic_write_bytes(path: str, data: bytes, fsync: bool = True) -> None:
    """Write ``data`` to ``path`` atomically (tmp + fsync + rename)."""
    tmp = _tmp_path(path)
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            if fsync:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        # Never leave a stray tmp behind a failed write (readers ignore
        # dotfiles, but a crash loop would accumulate them).
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def atomic_write_text(path: str, text: str, fsync: bool = True) -> None:
    atomic_write_bytes(path, text.encode("utf-8"), fsync=fsync)


def atomic_write_json(
    path: str, obj: Any, fsync: bool = True, **json_kwargs: Any
) -> None:
    """``json.dump`` with the atomic protocol (the manifest idiom)."""
    atomic_write_text(path, json.dumps(obj, **json_kwargs), fsync=fsync)


@contextlib.contextmanager
def atomic_writer(
    path: str, mode: str = "w", fsync: bool = True
) -> Iterator[Any]:
    """STREAMING atomic write: yields the tmp file object, commits via
    rename on clean exit, unlinks on error.  For payloads too large to
    materialize as one string/bytes (a merged multi-process trace, a
    long results JSONL) — ``json.dump(doc, f)`` straight into the tmp
    file keeps peak memory at the document, not document + serialization.
    """
    tmp = _tmp_path(path)
    try:
        with open(tmp, mode) as f:
            yield f
            if fsync:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def fsync_dir(path: str) -> None:
    """fsync a DIRECTORY so a completed rename inside it is durable.

    Best-effort: some filesystems/platforms refuse O_DIRECTORY fsync;
    the rename is still atomic, only its durability window widens.
    """
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)
