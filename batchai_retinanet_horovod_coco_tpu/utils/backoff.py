"""Bounded retry/backoff policy — ONE schedule implementation repo-wide.

Extracted from the machinery ``bench.py`` grew around its device probe
(ISSUE 12 satellite): bounded attempts, a geometric (or explicitly
listed) delay schedule with a ceiling, and *deterministic-seeded* jitter
so two processes never thundering-herd a recovering dependency while a
test can still pin the exact schedule.  Consumers:

- ``bench.py``'s availability probe (the original call site — env knobs
  ``BENCH_PROBE_ATTEMPTS`` / ``BENCH_PROBE_BACKOFF_S`` build a policy);
- the fleet router's health poller and circuit-breaker half-open probe
  cadence (serve/fleet.py) — there the policy is *consulted* for delays
  against an injectable clock, never slept on, so the breaker state
  machine is testable without wall time;
- the router's re-dispatch path (one bounded retry on another replica).

The policy object is frozen and stateless: ``delay_s(attempt)`` is a
pure function of (policy, attempt), so the full schedule is reproducible
from the seed alone (``delays()`` returns it whole; the unit test pins
it exactly).
"""

from __future__ import annotations

import dataclasses
import random
import time
from typing import Callable, Sequence


@dataclasses.dataclass(frozen=True)
class BackoffPolicy:
    """Bounded-retry schedule: ``max_tries`` attempts, ``max_tries - 1``
    sleeps between them.

    Delay for attempt ``i`` (0-based, i.e. the sleep AFTER the i-th
    failure) is ``min(ceiling_s, base_s * multiplier**i)`` — or
    ``schedule[min(i, len-1)]`` when an explicit ``schedule`` overrides
    the geometric rule (the bench probe's "10,30" env grammar: last
    value reused past the end).  ``jitter`` then scales it by a
    uniform factor in ``[1 - jitter, 1 + jitter]`` drawn from a
    per-(seed, attempt) RNG, so the schedule is deterministic given the
    seed but decorrelated across seeds (replicas seed from their id).
    """

    max_tries: int = 3
    base_s: float = 0.5
    multiplier: float = 2.0
    ceiling_s: float = 30.0
    jitter: float = 0.0  # ± fraction of the pre-jitter delay
    seed: int = 0
    schedule: tuple[float, ...] | None = None  # explicit delays override

    def __post_init__(self):
        if self.max_tries < 1:
            raise ValueError(f"max_tries must be >= 1, got {self.max_tries}")
        if not 0.0 <= self.jitter < 1.0:
            raise ValueError(f"jitter must be in [0, 1), got {self.jitter}")
        if self.schedule is not None and not self.schedule:
            raise ValueError("explicit schedule must be non-empty")

    def delay_s(self, attempt: int) -> float:
        """The sleep after the ``attempt``-th failure (0-based).  Pure:
        the same (policy, attempt) always yields the same delay."""
        attempt = max(0, int(attempt))
        if self.schedule is not None:
            d = float(self.schedule[min(attempt, len(self.schedule) - 1)])
        elif self.multiplier <= 1.0 or self.base_s <= 0.0:
            d = min(self.ceiling_s, self.base_s * self.multiplier**attempt)
        else:
            # Growing schedules multiply ITERATIVELY, stopping at the
            # ceiling: the closed form ``base * multiplier**attempt``
            # overflows a float near attempt ~1024, and long-lived
            # consumers (the fleet breaker's open counter against a
            # permanently dead replica) legitimately reach that.
            d = self.base_s
            left = attempt
            while d < self.ceiling_s and left > 0:
                d *= self.multiplier
                left -= 1
            d = min(d, self.ceiling_s)
        if self.jitter > 0.0:
            # Deterministic per-(seed, attempt): reproducible schedules,
            # decorrelated across seeds — no thundering herd, no flaky
            # test.  The mixing constant keeps adjacent seeds apart.
            rng = random.Random(self.seed * 1_000_003 + attempt)
            d *= 1.0 + self.jitter * rng.uniform(-1.0, 1.0)
        return max(0.0, d)

    def delays(self) -> list[float]:
        """The whole between-attempt schedule (``max_tries - 1`` sleeps)."""
        return [self.delay_s(i) for i in range(self.max_tries - 1)]

    def retry(
        self,
        fn: Callable[[], object],
        ok: Callable[[object], bool] = lambda r: r is None,
        sleep: Callable[[float], None] = time.sleep,
    ) -> tuple[int, object]:
        """Call ``fn`` up to ``max_tries`` times, sleeping the schedule
        between failures; returns ``(attempts_used, last_result)``.

        ``ok(result)`` decides success (default: the bench-probe
        convention — None means reachable, anything else is the error).
        Exceptions propagate immediately: this is the result-style retry
        loop; wrap the callable if exceptions should count as failures.
        """
        last: object = None
        for i in range(self.max_tries):
            last = fn()
            if ok(last):
                return i + 1, last
            if i + 1 < self.max_tries:
                sleep(self.delay_s(i))
        return self.max_tries, last

    @classmethod
    def from_env_schedule(
        cls, attempts: int, schedule_csv: str, default: Sequence[float] = (10.0,)
    ) -> "BackoffPolicy":
        """The bench probe's env grammar: an attempt count plus a comma
        list of seconds ("10,30"), last value reused; no jitter (the
        probe predates the policy and its tests pin unjittered sleeps)."""
        parsed = tuple(
            float(x) for x in schedule_csv.split(",") if x.strip()
        ) or tuple(default)
        return cls(max_tries=max(1, attempts), schedule=parsed)


__all__ = ["BackoffPolicy"]
