"""Host-side utilities: checkpointing, metrics, atomic artifact IO.

The reference's equivalents (SURVEY.md §5): Keras ``ModelCheckpoint`` on
rank 0 (§5.4), TensorBoard scalar callbacks + Horovod MetricAverage (§5.5),
and nothing for profiling beyond stdout (§5.1).

Attribute access is lazy (PEP 562): ``utils.checkpoint`` imports jax, but
``utils.atomicio`` must stay importable from jax-free processes (shm decode
workers, obs.trace, the analysis package) — an eager ``from ...checkpoint
import`` here would drag jax into all of them.
"""

from typing import Any

__all__ = ["CheckpointManager", "MetricLogger", "latest_step"]


def __getattr__(name: str) -> Any:
    if name in ("CheckpointManager", "latest_step"):
        from batchai_retinanet_horovod_coco_tpu.utils import checkpoint

        return getattr(checkpoint, name)
    if name == "MetricLogger":
        from batchai_retinanet_horovod_coco_tpu.utils.metrics import (
            MetricLogger,
        )

        return MetricLogger
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
