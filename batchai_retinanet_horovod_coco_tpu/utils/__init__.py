"""Host-side utilities: checkpointing, metrics, profiling.

The reference's equivalents (SURVEY.md §5): Keras ``ModelCheckpoint`` on
rank 0 (§5.4), TensorBoard scalar callbacks + Horovod MetricAverage (§5.5),
and nothing for profiling beyond stdout (§5.1).
"""

from batchai_retinanet_horovod_coco_tpu.utils.checkpoint import (
    CheckpointManager,
    latest_step,
)
from batchai_retinanet_horovod_coco_tpu.utils.metrics import MetricLogger

__all__ = ["CheckpointManager", "MetricLogger", "latest_step"]
