"""Structured training metrics: JSONL always, TensorBoard when available.

Replaces the reference's observability stack (SURVEY.md §5.5): Keras progbar
per rank + TensorBoard callback + Horovod ``MetricAverageCallback``.  Here
cross-replica averaging already happened ON DEVICE inside the train step
(``lax.pmean``, train/step.py), so the logger is a process-0-only sink:
one JSONL line per log event (machine-readable, the era's TensorBoard
equivalent for this air-gapped environment) plus optional tf.summary output
when TensorFlow is importable, plus a human line on stdout.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Mapping

import jax
import numpy as np


def _scalarize(metrics: Mapping[str, Any]) -> dict[str, float]:
    out = {}
    for k, v in metrics.items():
        try:
            out[k] = float(np.asarray(v))
        except (TypeError, ValueError):
            continue
    return out


class MetricLogger:
    """Process-0 metric sink: JSONL file + stdout + optional TensorBoard."""

    def __init__(
        self,
        log_dir: str | None,
        tensorboard: bool = False,
        stdout: bool = True,
        only_process_zero: bool = True,
    ):
        self._enabled = (not only_process_zero) or jax.process_index() == 0
        self._stdout = stdout
        self._jsonl = None
        self._tb = None
        self._t0 = time.time()
        if not self._enabled:
            return
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            self._jsonl = open(os.path.join(log_dir, "metrics.jsonl"), "a")
            if tensorboard:
                try:
                    import tensorflow as tf  # heavyweight; only on request

                    self._tb = tf.summary.create_file_writer(
                        os.path.join(log_dir, "tb")
                    )
                except ImportError:
                    self._tb = None

    def log(self, step: int, metrics: Mapping[str, Any], prefix: str = "train") -> None:
        if not self._enabled:
            return
        scalars = _scalarize(metrics)
        if self._jsonl:
            rec = {"step": step, "wall_s": round(time.time() - self._t0, 3)}
            rec.update({f"{prefix}/{k}": v for k, v in scalars.items()})
            self._jsonl.write(json.dumps(rec) + "\n")
            self._jsonl.flush()
        if self._tb is not None:
            import tensorflow as tf

            with self._tb.as_default():
                for k, v in scalars.items():
                    tf.summary.scalar(f"{prefix}/{k}", v, step=step)
            self._tb.flush()
        if self._stdout:
            parts = " ".join(f"{k}={v:.4g}" for k, v in sorted(scalars.items()))
            print(f"[{prefix} step {step}] {parts}", flush=True)

    def close(self) -> None:
        if self._jsonl:
            self._jsonl.close()
            self._jsonl = None
        if self._tb is not None:
            self._tb.close()
            self._tb = None
