"""Training metrics: thin compat shim over the obs event sink.

This module WAS the whole observability stack (an 89-line process-0 JSONL
scalar sink, replacing the reference's Keras progbar + TensorBoard
callbacks, SURVEY.md §5.5).  ISSUE 3 grew that into the ``obs`` subsystem
(``obs/events.py``: run-header records, counters/gauges, device memory,
compile events; ``obs/trace.py``: spans on the same clock) and this file
keeps the old import surface alive: ``MetricLogger`` is now a name for
``EventSink`` with the historical constructor defaults, so every existing
caller (train.py, the loop, the pod tests) keeps working while gaining the
run header, aligned monotonic timestamps, loud NaN passthrough, and
counted (never silent) metric drops.

New code should import ``EventSink`` / ``split_runs`` from
``batchai_retinanet_horovod_coco_tpu.obs.events`` directly.
"""

from __future__ import annotations

from typing import Any, Mapping

from batchai_retinanet_horovod_coco_tpu.obs.events import (
    EventSink,
    split_runs,
)

__all__ = ["MetricLogger", "EventSink", "split_runs", "_scalarize"]


def _scalarize(metrics: Mapping[str, Any]) -> dict[str, float]:
    """Historical signature (dict only).  Semantics match the pre-ISSUE-3
    version (non-finite values always converted fine; only non-castable
    values drop) — what changed is that drops are now COUNTED AND NAMED
    by ``obs.events.scalarize`` and the sink announces non-finite values
    loudly instead of printing them indistinguishably."""
    from batchai_retinanet_horovod_coco_tpu.obs.events import scalarize

    return scalarize(metrics)[0]


class MetricLogger(EventSink):
    """The historical process-0 sink name; see module docstring."""

    def __init__(
        self,
        log_dir: str | None,
        tensorboard: bool = False,
        stdout: bool = True,
        only_process_zero: bool = True,
        run_config: Mapping[str, Any] | None = None,
    ):
        super().__init__(
            log_dir,
            tensorboard=tensorboard,
            stdout=stdout,
            only_process_zero=only_process_zero,
            run_config=run_config,
        )
