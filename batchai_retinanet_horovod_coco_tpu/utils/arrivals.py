"""Seeded open-loop arrival schedules — the shared load-shape vocabulary.

``bench.py --mode serve`` introduced the mixed steady → burst → lull
schedule as a private helper (ISSUE 14: the load shape that exposes
deadline-only partial-batch waste); the streaming leg (ISSUE 18) needs
the SAME generator for per-stream frame traces plus a multi-stream
composition, and a bench-private copy would drift.  One module, pure
NumPy, no serve imports — both bench legs and the stream smoke build
their offered load here, and the unit tests pin determinism per seed
(same seed ⇒ byte-identical schedule ⇒ comparable runs).
"""

from __future__ import annotations

import numpy as np

#: The canonical phase multipliers: steady → burst → lull, cycling.
MIXED_PHASES = (1.0, 1.8, 0.7)


def mixed_arrival_schedule(
    n: int,
    base_rate: float,
    seed: int = 0,
    phases: tuple[float, ...] = MIXED_PHASES,
) -> list[float]:
    """Seeded open-loop MIXED arrival times (absolute seconds): cycling
    steady → burst → lull phases of exponential inter-arrivals — the
    load shape that exposes deadline-only partial-batch waste (ISSUE
    14).  Same seed ⇒ same offered load, so two legs (continuous vs
    deadline, stream vs single-image) race the identical schedule."""
    rng = np.random.default_rng(seed)
    phase_len = max(1, n // 6)
    t, times = 0.0, []
    for i in range(n):
        rate = base_rate * phases[(i // phase_len) % len(phases)]
        t += float(rng.exponential(1.0 / rate))
        times.append(t)
    return times


def multi_stream_schedule(
    n_streams: int,
    frames_per_stream: int,
    fps: float,
    seed: int = 0,
    jitter: float = 0.25,
) -> list[list[float]]:
    """Per-stream frame arrival times for ``n_streams`` concurrent video
    sessions (absolute seconds, one sorted list per stream).

    Video is NOT Poisson: frames tick at ~``fps`` with bounded capture
    jitter, and streams start staggered (stream k opens k/fps seconds
    in, so session opens don't align artificially).  Jitter is drawn
    from the SAME seeded generator family as the mixed schedule — the
    whole multi-stream trace is a pure function of ``seed``."""
    rng = np.random.default_rng(seed)
    period = 1.0 / max(1e-9, fps)
    streams = []
    for k in range(n_streams):
        start = k * period / max(1, n_streams)
        offsets = rng.uniform(
            -jitter * period, jitter * period, size=frames_per_stream
        )
        times = [
            max(0.0, start + i * period + float(offsets[i]))
            for i in range(frames_per_stream)
        ]
        # Capture jitter must never reorder frames: a video client sends
        # frame i before frame i+1 by construction.
        times.sort()
        streams.append(times)
    return streams


__all__ = ["MIXED_PHASES", "mixed_arrival_schedule", "multi_stream_schedule"]
