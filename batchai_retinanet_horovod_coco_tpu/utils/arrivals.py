"""Seeded open-loop arrival schedules — the shared load-shape vocabulary.

``bench.py --mode serve`` introduced the mixed steady → burst → lull
schedule as a private helper (ISSUE 14: the load shape that exposes
deadline-only partial-batch waste); the streaming leg (ISSUE 18) needs
the SAME generator for per-stream frame traces plus a multi-stream
composition, and a bench-private copy would drift.  One module, pure
NumPy, no serve imports — both bench legs and the stream smoke build
their offered load here, and the unit tests pin determinism per seed
(same seed ⇒ byte-identical schedule ⇒ comparable runs).
"""

from __future__ import annotations

import numpy as np

#: The canonical phase multipliers: steady → burst → lull, cycling.
MIXED_PHASES = (1.0, 1.8, 0.7)


def mixed_arrival_schedule(
    n: int,
    base_rate: float,
    seed: int = 0,
    phases: tuple[float, ...] = MIXED_PHASES,
) -> list[float]:
    """Seeded open-loop MIXED arrival times (absolute seconds): cycling
    steady → burst → lull phases of exponential inter-arrivals — the
    load shape that exposes deadline-only partial-batch waste (ISSUE
    14).  Same seed ⇒ same offered load, so two legs (continuous vs
    deadline, stream vs single-image) race the identical schedule."""
    rng = np.random.default_rng(seed)
    phase_len = max(1, n // 6)
    t, times = 0.0, []
    for i in range(n):
        rate = base_rate * phases[(i // phase_len) % len(phases)]
        t += float(rng.exponential(1.0 / rate))
        times.append(t)
    return times


#: One spike window per period: (center_frac, width_frac, multiplier).
DIURNAL_SPIKES = ((0.5, 0.15, 3.0),)


def diurnal_spike_schedule(
    n: int,
    base_rate: float,
    seed: int = 0,
    period_s: float = 60.0,
    amplitude: float = 0.5,
    spikes: tuple[tuple[float, float, float], ...] = DIURNAL_SPIKES,
) -> list[float]:
    """Seeded diurnal + spike open-loop arrival times (ISSUE 19) — the
    load shape an autoscaler must follow: a sinusoidal base rate (the
    compressed "day", one cycle per ``period_s``) with multiplicative
    burst windows riding on it.  ``spikes`` are per-period windows
    ``(center_frac, width_frac, multiplier)`` in period-fraction units;
    ``amplitude < 1`` keeps the off-peak rate positive so the schedule
    always terminates.  Exponential inter-arrivals at the instantaneous
    rate, same generator family as ``mixed_arrival_schedule`` — one
    seed pins the entire offered-load trace, so the chaos leg and the
    SERVEBENCH autoscale leg replay the identical day."""
    if not 0.0 <= amplitude < 1.0:
        raise ValueError(f"amplitude must be in [0, 1), got {amplitude}")
    rng = np.random.default_rng(seed)
    t, times = 0.0, []
    for _ in range(n):
        frac = (t % period_s) / period_s
        rate = base_rate * (1.0 + amplitude * np.sin(2.0 * np.pi * frac))
        for center, width, mult in spikes:
            # Wrap-aware distance on the unit circle of the period.
            d = abs(frac - center)
            if min(d, 1.0 - d) <= width / 2.0:
                rate *= mult
        t += float(rng.exponential(1.0 / max(rate, 1e-9)))
        times.append(t)
    return times


def multi_stream_schedule(
    n_streams: int,
    frames_per_stream: int,
    fps: float,
    seed: int = 0,
    jitter: float = 0.25,
) -> list[list[float]]:
    """Per-stream frame arrival times for ``n_streams`` concurrent video
    sessions (absolute seconds, one sorted list per stream).

    Video is NOT Poisson: frames tick at ~``fps`` with bounded capture
    jitter, and streams start staggered (stream k opens k/fps seconds
    in, so session opens don't align artificially).  Jitter is drawn
    from the SAME seeded generator family as the mixed schedule — the
    whole multi-stream trace is a pure function of ``seed``."""
    rng = np.random.default_rng(seed)
    period = 1.0 / max(1e-9, fps)
    streams = []
    for k in range(n_streams):
        start = k * period / max(1, n_streams)
        offsets = rng.uniform(
            -jitter * period, jitter * period, size=frames_per_stream
        )
        times = [
            max(0.0, start + i * period + float(offsets[i]))
            for i in range(frames_per_stream)
        ]
        # Capture jitter must never reorder frames: a video client sends
        # frame i before frame i+1 by construction.
        times.sort()
        streams.append(times)
    return streams


__all__ = [
    "DIURNAL_SPIKES",
    "MIXED_PHASES",
    "diurnal_spike_schedule",
    "mixed_arrival_schedule",
    "multi_stream_schedule",
]
