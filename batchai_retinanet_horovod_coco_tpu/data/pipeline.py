"""Host input pipeline: decode → augment → resize → bucket-pad → batch.

Parity target: keras-retinanet's ``Generator`` hot loop (SURVEY.md M8, call
stack 3.3) — JPEG decode, random flip, aspect-preserving resize to
min-side/max-side (800/1333 for the flagship config, BASELINE.json:10), and
batching — minus everything the TPU rebuild moves on device (anchor targets).

TPU-first redesign decisions:
- **Static shape buckets** (SURVEY.md §7.3 hard part 1): every image is
  resized (aspect preserved) then padded into one of a small set of fixed
  (H, W) buckets chosen by aspect ratio; batches are formed within a bucket,
  so XLA compiles one program per bucket instead of one per unique padded
  shape.
- GT boxes are padded to a fixed ``max_gt`` with a validity mask; target
  assignment happens on device.
- Normalization is ImageNet-style RGB mean/std (a redesign of the reference's
  caffe BGR mean-subtract; the convention only needs to match the backbone
  init, which is ours).
- Deterministic: one PRNG per (seed, epoch); multi-host sharding is plain
  index sharding by ``process_index`` (the grain/tf.data idiom), replacing
  the reference's implicit per-rank generator seeding.
- Decode + resize fan out over a thread pool; batches are prefetched by a
  background thread into a bounded queue (the reference used Keras'
  ``fit_generator`` worker pool).
"""

from __future__ import annotations

import dataclasses
import logging
import queue
import threading
from collections.abc import Iterator
from concurrent.futures import ThreadPoolExecutor
from typing import NamedTuple

import numpy as np

logger = logging.getLogger(__name__)

from batchai_retinanet_horovod_coco_tpu.data.transforms import cv2  # shared fallback

from batchai_retinanet_horovod_coco_tpu.data.coco import CocoDataset, ImageRecord
from batchai_retinanet_horovod_coco_tpu.obs import trace, watchdog
from batchai_retinanet_horovod_coco_tpu.data.transforms import (
    TransformConfig,
    apply_random_transform,
)

IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], dtype=np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], dtype=np.float32)
# Normalize as two fused in-place passes: x*scale - offset == (x/255-m)/s.
_NORM_SCALE = (1.0 / (255.0 * IMAGENET_STD)).astype(np.float32)
_NORM_OFFSET = (IMAGENET_MEAN / IMAGENET_STD).astype(np.float32)
# uint8 batch padding ≈ the dataset mean, i.e. ~0.0 in normalized space —
# matching the reference's pad-with-zeros-AFTER-preprocessing semantics.
_PAD_PIXEL = np.round(IMAGENET_MEAN * 255.0).astype(np.uint8)


def normalize_images(images):
    """Device-side ImageNet normalization for uint8 image batches.

    TPU-first redesign of the reference's host-side ``preprocess_image``
    (SURVEY.md M8): the pipeline ships uint8 (4x less host work, host RAM
    and PCIe traffic); this cast+scale runs on device, where XLA fuses it
    into the stem conv's input. f32 inputs pass through unchanged
    (pre-normalized arrays from tests/tools keep working).
    """
    import jax.numpy as jnp

    if images.dtype != jnp.uint8:
        return images
    x = images.astype(jnp.float32)
    return x * jnp.asarray(_NORM_SCALE) - jnp.asarray(_NORM_OFFSET)


@dataclasses.dataclass(frozen=True)
class PipelineConfig:
    batch_size: int = 2
    # (H, W) buckets; an image goes to the first bucket whose aspect class
    # matches (landscape → wide bucket, portrait → tall, near-square → square).
    buckets: tuple[tuple[int, int], ...] = ((800, 1344), (1344, 800), (1024, 1024))
    min_side: int = 800
    max_side: int = 1333
    max_gt: int = 100
    hflip_prob: float = 0.5
    # Full random-affine + photometric augmentation (the reference's
    # --random-transform recipe, data/transforms.py). When set, it replaces
    # the flip-only path — configure flips via TransformConfig.flip_x_prob.
    transform: TransformConfig | None = None
    shuffle: bool = True
    seed: int = 0
    # Multi-host sharding: this process sees records[shard_index::shard_count].
    shard_index: int = 0
    shard_count: int = 1
    num_workers: int = 8
    # > 0 selects the multiprocess shared-memory pipeline (shm_pipeline.py):
    # that many decode/augment/resize worker PROCESSES writing into
    # preallocated shared-memory ring buffers, sidestepping the GIL ceiling
    # of the thread pool (PIL JPEG decode holds the GIL; the per-worker
    # thread sweep plateaus at 2).  0 (default) keeps the in-process thread
    # pool — the right choice under pytest and on low-resource hosts.
    # Both paths emit bit-identical batches for a fixed seed.
    num_worker_procs: int = 0
    # Bounded-stall watchdog for the multiprocess path: a worker crash is
    # detected via liveness within ~0.2 s, and a WEDGED (alive but stuck)
    # worker surfaces as a raised exception after this many seconds of a
    # head-of-line batch making no progress — never a silent hang.
    worker_timeout: float = 120.0
    # multiprocessing start method for the worker processes.  "spawn" is the
    # default: forking a process that has initialized JAX/XLA (thread pools,
    # possibly a TPU client) is unsafe; spawned workers import only the data
    # layer (numpy/PIL/cv2), never jax.
    mp_start_method: str = "spawn"
    prefetch: int = 4
    drop_remainder: bool = True
    # Elastic resume (ISSUE 11): skip this many ALREADY-CONSUMED batches
    # before emitting the first one (train only).  Batch composition is a
    # pure function of (seed, epoch, shard) — ``batch_plans`` — so skipping
    # k plans without decoding re-derives the exact stream position of a
    # run that consumed k batches: no batch replayed, none skipped.  The
    # train loop consumes one batch per process per step, so a resume at
    # step r passes r here (train.py --resume-elastic).
    skip_batches: int = 0
    # Self-healing numerics resume (ISSUE 11): source image_ids that must
    # never be emitted again — ``--auto-resume`` passes the poison batch's
    # ids from NUMERICS_DUMP.json so the batch that tripped the abort
    # cannot recur.  Applied after the epoch shuffle, before sharding, in
    # ``epoch_indices`` (shared by the thread and shm producers).
    exclude_ids: tuple[int, ...] = ()
    # Default: ship uint8 and normalize ON DEVICE (see normalize_images).
    # True restores the reference's host-side f32 preprocessing.
    host_normalize: bool = False


def dataset_max_gt(dataset) -> int:
    """Largest per-image annotation count in the dataset (crowds excluded —
    only ``record.boxes`` feed training targets)."""
    return max((len(r.boxes) for r in dataset.records), default=0)


def resolve_max_gt(requested: int | None, *datasets, cap: int = 512) -> int:
    """The pipeline's gt-padding size for a run.

    ``None`` (auto) sizes to the datasets' true per-image maximum — no
    silent truncation, COCO images can carry >100 boxes — rounded up to a
    multiple of 8 for layout friendliness and clamped to [8, cap].  An
    explicit value is honored as-is; ``build_pipeline`` then counts and
    logs what it drops.
    """
    if requested is not None:
        return requested
    need = max((dataset_max_gt(ds) for ds in datasets), default=0)
    return max(8, min(round_up(max(need, 1), 8), cap))


@dataclasses.dataclass
class PipelineStats:
    """Mutable counters a pipeline exposes (``.stats`` on the iterator).

    Truncation means an image carried more than ``max_gt`` boxes: the
    overflow boxes vanish from the training targets (their anchors become
    background and are actively penalized), so it must be visible.
    """

    truncated_boxes: int = 0
    truncated_images: int = 0


class Batch(NamedTuple):
    images: np.ndarray  # (B, H, W, 3) uint8 raw (device normalizes; see
    # normalize_images) or float32 pre-normalized when host_normalize=True
    gt_boxes: np.ndarray  # (B, max_gt, 4) float32, resized coords
    gt_labels: np.ndarray  # (B, max_gt) int32
    gt_mask: np.ndarray  # (B, max_gt) bool
    image_ids: np.ndarray  # (B,) int64
    scales: np.ndarray  # (B,) float32: resized / original
    valid: np.ndarray  # (B,) bool: False for eval padding rows


def round_up(x: int, multiple: int) -> int:
    return ((x + multiple - 1) // multiple) * multiple


def stop_gated_put(q: queue.Queue, item, stop: threading.Event) -> bool:
    """Blocking put into a bounded queue that aborts when ``stop`` is set.

    The one producer→consumer handoff idiom every pipeline producer in this
    package uses (thread pool, shm coordinator, device-prefetch feeder): a
    plain blocking put would leak the producer thread forever if the
    consumer disappears while the queue is full.  Returns False on abort.
    """
    while not stop.is_set():
        try:
            q.put(item, timeout=0.1)
            return True
        except queue.Full:
            continue
    return False


def default_buckets(min_side: int, max_side: int) -> tuple[tuple[int, int], ...]:
    """Static (H, W) shape buckets covering the resize rule's output range.

    The single source of truth for bucket derivation — train.py, debug.py
    and bench.py all consume this, so the shapes the tools report match the
    shapes the train step compiles for.

    Two buckets suffice, PROVABLY: ``resize_scale`` maps every source to
    resized dims with min(rh, rw) <= min_side <= lo and max(rh, rw) <=
    max_side <= hi, so a landscape/square result (rh <= rw) always fits
    (lo, hi) and a portrait result fits (hi, lo).  Rounds 1-4 carried a
    third round_up((lo+hi)/2) "mid" square bucket for mild portraits; the
    round-5 exhaustive source-size scan (tests/unit/test_buckets.py)
    showed it is UNREACHABLE under that argument for every config — and
    for the images it targeted the portrait bucket pads less anyway
    (933x800 resized: 0.33 Mpx waste in 1344x800 vs 0.44 in 1088x1088).
    Dropping it removes a dead compiled program per run (one fewer
    ~minutes-long bucket compile at pod bring-up, a third off the bench
    sweep) and a phantom 4% share in the weighted-mix arithmetic.
    """
    lo = round_up(min_side, 32)
    hi = round_up(max_side, 32)
    if lo == hi:
        return ((lo, lo),)
    return ((lo, hi), (hi, lo))


def resize_scale(h: int, w: int, min_side: int, max_side: int) -> float:
    """Reference resize rule: scale so min side = min_side, capped by max_side."""
    scale = min_side / min(h, w)
    if scale * max(h, w) > max_side:
        scale = max_side / max(h, w)
    return scale


def bucket_for_source(
    h: int,
    w: int,
    min_side: int,
    max_side: int,
    buckets: tuple[tuple[int, int], ...],
) -> tuple[int, int]:
    """Bucket a SOURCE-resolution image lands in: the pipeline's own
    resize rule + rounding + bucket pick, in one place — shared by the
    pipeline's batch former and by ``debug.py buckets`` so the measured
    bucket shares cannot drift from what the producer actually does."""
    scale = resize_scale(h, w, min_side, max_side)
    return pick_bucket(int(round(h * scale)), int(round(w * scale)), buckets)


def pick_bucket(
    h: int, w: int, buckets: tuple[tuple[int, int], ...]
) -> tuple[int, int]:
    """Smallest bucket that fits (h, w); falls back to the largest-area one."""
    fitting = [b for b in buckets if b[0] >= h and b[1] >= w]
    if fitting:
        return min(fitting, key=lambda b: b[0] * b[1])
    return max(buckets, key=lambda b: b[0] * b[1])


def resize_for_bucket(
    image: np.ndarray,
    bucket: tuple[int, int],
    min_side: int,
    max_side: int,
) -> tuple[np.ndarray, float]:
    """Aspect-preserving resize of ONE decoded uint8 HWC image into
    ``bucket`` — the single source of truth for inference-time geometry,
    shared by ``load_example`` (train/eval pipeline) and the serve
    router (serve/router.py), so a served image can never be resized
    differently from the eval pipeline that pinned the model's metrics.

    Applies the reference resize rule (``resize_scale``) capped so the
    result fits the bucket (extreme aspect ratios).  Returns
    ``(image, scale)``; when no resize is needed the input array is
    returned as-is and boxes must NOT be rescaled (callers key off the
    shape changing, matching the historical behavior bit-for-bit).
    """
    h, w = image.shape[:2]
    bh, bw = bucket
    scale = min(resize_scale(h, w, min_side, max_side), bh / h, bw / w)
    nh = min(bh, int(round(h * scale)))
    nw = min(bw, int(round(w * scale)))
    if (nh, nw) != (h, w):
        if cv2 is not None:  # ~3x PIL for bilinear resize; releases the GIL
            image = cv2.resize(image, (nw, nh), interpolation=cv2.INTER_LINEAR)
        else:
            from PIL import Image

            image = np.asarray(
                Image.fromarray(image).resize((nw, nh), Image.BILINEAR),
                dtype=np.uint8,
            )
    return image, scale


def load_example(
    dataset: CocoDataset,
    record: ImageRecord,
    config: PipelineConfig,
    rng: np.random.Generator | None,
    bucket: tuple[int, int],
) -> tuple[np.ndarray, np.ndarray, np.ndarray, float]:
    """Decode + (train-only) flip + resize one image into ``bucket``.

    Returns (image HWC — raw uint8 by default, f32 normalized when
    ``config.host_normalize`` — boxes (N,4) resized, labels, scale).
    The image is NOT yet padded to the bucket, but is guaranteed to fit it:
    when no bucket fits the reference resize rule (extreme aspect ratios),
    the scale is capped so the image fits the one the producer chose.
    """
    from PIL import Image

    with Image.open(dataset.image_path(record)) as im:
        image = np.asarray(im.convert("RGB"), dtype=np.uint8)
    boxes = record.boxes.copy()
    labels = record.labels.copy()
    h, w = image.shape[:2]

    if rng is not None and config.transform is not None:
        image, boxes, labels = apply_random_transform(
            image, boxes, labels, config.transform, rng
        )
    elif rng is not None and config.hflip_prob > 0 and rng.random() < config.hflip_prob:
        image = image[:, ::-1]
        x1 = boxes[:, 0].copy()
        boxes[:, 0] = w - boxes[:, 2]
        boxes[:, 2] = w - x1

    image, scale = resize_for_bucket(
        image, bucket, config.min_side, config.max_side
    )
    if image.shape[:2] != (h, w):
        boxes = boxes * scale
    if config.host_normalize:
        image = image.astype(np.float32)
        image *= _NORM_SCALE
        image -= _NORM_OFFSET
    return image, boxes, labels, scale


_PAD_TEMPLATES: dict[tuple[int, int], np.ndarray] = {}


def _pad_template(bh: int, bw: int) -> np.ndarray:
    """Contiguous (bh, bw, 3) uint8 array of the pad pixel, cached per
    bucket shape.

    Assigning the raw (3,) ``_PAD_PIXEL`` into a strided destination takes
    numpy's generic inner loop — measured 21 ms/batch at the flagship
    bucket, dwarfing the actual image copies (~5 ms) and, in the thread
    path, all of it spent HOLDING THE GIL inside the producer.  Copying
    from a materialized template is a plain strided memcpy (~1 ms).
    """
    tmpl = _PAD_TEMPLATES.get((bh, bw))
    if tmpl is None:
        tmpl = np.empty((bh, bw, 3), dtype=np.uint8)
        for c in range(3):
            tmpl[..., c] = _PAD_PIXEL[c]
        _PAD_TEMPLATES[(bh, bw)] = tmpl
    return tmpl


def _assemble(
    examples: list[tuple[np.ndarray, np.ndarray, np.ndarray, float]],
    image_ids: list[int],
    bucket: tuple[int, int],
    config: PipelineConfig,
    stats: PipelineStats | None = None,
) -> Batch:
    b = len(examples)
    bh, bw = bucket
    if config.host_normalize:
        images = np.zeros((b, bh, bw, 3), dtype=np.float32)
    else:
        # Pad with the dataset-mean pixel == ~0.0 in normalized space (the
        # reference padded with zeros AFTER preprocessing).  Only the pad
        # MARGINS are filled below — at the flagship bucket the image covers
        # most of the slot, so a full-slab prefill would roughly double the
        # assembly's memory traffic for bytes that are then overwritten.
        images = np.empty((b, bh, bw, 3), dtype=np.uint8)
    pad = None if config.host_normalize else _pad_template(bh, bw)
    gt_boxes = np.zeros((b, config.max_gt, 4), dtype=np.float32)
    gt_labels = np.zeros((b, config.max_gt), dtype=np.int32)
    gt_mask = np.zeros((b, config.max_gt), dtype=bool)
    scales = np.zeros((b,), dtype=np.float32)
    for i, (img, boxes, labels, scale) in enumerate(examples):
        h, w = img.shape[:2]
        images[i, :h, :w] = img
        if pad is not None:
            if h < bh:
                images[i, h:] = pad[h:]
            if w < bw:
                images[i, :h, w:] = pad[:h, w:]
        n = min(len(boxes), config.max_gt)
        if stats is not None and len(boxes) > n:
            stats.truncated_boxes += len(boxes) - n
            stats.truncated_images += 1
        gt_boxes[i, :n] = boxes[:n]
        gt_labels[i, :n] = labels[:n]
        gt_mask[i, :n] = True
        scales[i] = scale
    return Batch(
        images=images,
        gt_boxes=gt_boxes,
        gt_labels=gt_labels,
        gt_mask=gt_mask,
        image_ids=np.asarray(image_ids, dtype=np.int64),
        scales=scales,
        valid=np.ones((b,), dtype=bool),
    )


def example_rng(
    config: PipelineConfig, train: bool, epoch: int, idx: int
) -> np.random.Generator | None:
    """Per-example PRNG keyed on (seed, epoch, idx) — the determinism
    contract both the thread and multiprocess producers share: an example's
    augmentation depends only on these three ints, never on which worker
    (thread OR process) happened to decode it."""
    if not train:
        return None
    return np.random.default_rng(
        np.random.SeedSequence([config.seed, epoch, idx])
    )


def epoch_indices(
    dataset, config: PipelineConfig, train: bool, epoch: int
) -> list[int]:
    """This shard's record indices for ``epoch``, shuffled per (seed, epoch).

    ``config.exclude_ids`` drops records AFTER the shuffle and before
    sharding: the (seed, epoch) permutation is unchanged, the excluded
    images simply leave holes — so the auto-resume exclusion perturbs the
    stream minimally and deterministically on every shard.
    """
    idx = np.arange(len(dataset.records))
    if train and config.shuffle:
        np.random.default_rng(
            np.random.SeedSequence([config.seed, epoch])
        ).shuffle(idx)
    if config.exclude_ids:
        excluded = {int(i) for i in config.exclude_ids}
        idx = np.asarray(
            [
                i
                for i in idx
                if int(dataset.records[i].image_id) not in excluded
            ],
            dtype=np.int64,
        )
    return list(idx[config.shard_index :: config.shard_count])


def batch_plans(
    dataset, config: PipelineConfig, train: bool, epoch: int
) -> Iterator[tuple[tuple[int, int], list[int], list[int], bool]]:
    """Deterministic batch composition for one epoch, shared by the thread
    and multiprocess producers so their emission order is identical by
    construction: yields (bucket, record_indices, image_ids, short) in the
    exact order batches are emitted."""
    indices = epoch_indices(dataset, config, train, epoch)
    by_bucket: dict[tuple[int, int], list[int]] = {}
    for i in indices:
        r = dataset.records[i]
        by_bucket.setdefault(
            bucket_for_source(
                r.height, r.width, config.min_side, config.max_side,
                config.buckets,
            ),
            [],
        ).append(i)
    for bucket, idxs in by_bucket.items():
        for start in range(0, len(idxs), config.batch_size):
            chunk = idxs[start : start + config.batch_size]
            if len(chunk) < config.batch_size and (
                train and config.drop_remainder
            ):
                continue
            ids = [dataset.records[i].image_id for i in chunk]
            short = not train and len(chunk) < config.batch_size
            yield bucket, chunk, ids, short


def _warn_truncation(dataset, config: PipelineConfig) -> None:
    over = sum(1 for r in dataset.records if len(r.boxes) > config.max_gt)
    if over:
        logger.warning(
            "max_gt=%d truncates %d/%d images (dataset max %d boxes/image); "
            "overflow boxes are DROPPED from training targets. Pass an "
            "explicit larger --max-gt to keep them.",
            config.max_gt, over, len(dataset.records), dataset_max_gt(dataset),
        )


class _PipelineIterator:
    """Iterator over batches exposing live ``stats`` (PipelineStats)."""

    def __init__(
        self, gen: Iterator[Batch], stats: PipelineStats, stop: threading.Event
    ):
        self._gen = gen
        self._stop = stop
        self.stats = stats

    def __iter__(self):
        return self

    def __next__(self) -> Batch:
        return next(self._gen)

    def close(self) -> None:
        """Stop the producer thread.

        Signals the stop event directly (generator ``.close()`` alone is a
        no-op on a never-started generator, which would leak the producer).
        """
        self._stop.set()
        self._gen.close()


def build_pipeline(
    dataset: CocoDataset,
    config: PipelineConfig,
    train: bool = True,
) -> _PipelineIterator:
    """Infinite (train) or single-epoch (eval) iterator of bucketed batches.

    Train: shuffles per epoch, groups records by bucket, yields full batches.
    Eval: preserves order, no augmentation, pads the final batch with
    ``valid=False`` rows so every record is evaluated exactly once.

    ``config.num_worker_procs > 0`` routes to the multiprocess shared-memory
    producer (shm_pipeline.py) — same batches, bit-identical for a fixed
    seed, decoded by worker processes instead of GIL-bound threads.
    """
    _warn_truncation(dataset, config)
    if config.num_worker_procs > 0:
        from batchai_retinanet_horovod_coco_tpu.data.shm_pipeline import (
            build_shm_pipeline,
        )

        return build_shm_pipeline(dataset, config, train)
    stats = PipelineStats()

    out: queue.Queue = queue.Queue(maxsize=max(1, config.prefetch))
    stop = threading.Event()
    _SENTINEL = object()

    def _put(item) -> bool:
        return stop_gated_put(out, item, stop)

    def producer() -> None:
        # watchdog-exempt (pool): decode-pool threads surface through
        # future.result() on THIS (registered) thread — a wedged decode
        # stalls the producer heartbeat, which is the attributable signal.
        pool = ThreadPoolExecutor(max_workers=config.num_workers)
        hb = watchdog.register(
            "pipe-producer", details=lambda: {"qsize": out.qsize()}
        )
        try:
            _produce(pool, hb)
        except BaseException as exc:  # propagate to the consumer; never hang
            _put(exc)
        finally:
            hb.close()
            pool.shutdown(wait=False)

    def _produce(pool: ThreadPoolExecutor, hb) -> None:
            from collections import deque

            # Keep several batches' decode futures in flight so the pool
            # never drains at a batch boundary (the naive submit-one-batch/
            # wait/assemble loop caps parallelism at batch_size and measured
            # ~11 imgs/s regardless of worker count).  Batches are EMITTED
            # in submission order — determinism is unchanged.
            max_inflight = max(
                2, -(-config.num_workers // max(1, config.batch_size)) + 1
            )
            inflight: deque = deque()

            def flush_one() -> bool:
                futures, ids, bucket, short = inflight.popleft()
                with trace.span("pipe_decode_wait"):
                    examples = [f.result() for f in futures]
                hb.beat()  # decode progress = fleet liveness
                with trace.span("pipe_assemble"):
                    batch = _assemble(examples, ids, bucket, config, stats)
                if short:
                    batch = _pad_batch(batch, config.batch_size)
                hb.idle()  # a full output queue is backpressure, not a stall
                ok = _put(batch)
                hb.beat()
                return ok

            epoch = 0
            # Elastic resume: already-consumed batches are skipped at the
            # PLAN level — no decode, no RNG draw, just plan arithmetic —
            # so fast-forwarding to step r costs milliseconds, not a
            # replay of r batches of JPEG work.
            to_skip = config.skip_batches if train else 0
            while not stop.is_set():
                for bucket, chunk, ids, short in batch_plans(
                    dataset, config, train, epoch
                ):
                    if to_skip > 0:
                        to_skip -= 1
                        continue
                    futures = [
                        pool.submit(
                            load_example,
                            dataset,
                            dataset.records[i],
                            config,
                            example_rng(config, train, epoch, int(i)),
                            bucket,
                        )
                        for i in chunk
                    ]
                    inflight.append((futures, ids, bucket, short))
                    if len(inflight) >= max_inflight and not flush_one():
                        return
                if not train:
                    while inflight:
                        if not flush_one():
                            return
                    _put(_SENTINEL)
                    return
                epoch += 1

    # watchdog: registers in producer() at thread start.
    thread = threading.Thread(
        target=producer, daemon=True, name="pipe-producer"
    )
    thread.start()

    def iterate() -> Iterator[Batch]:
        try:
            while True:
                item = out.get()
                if item is _SENTINEL:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop.set()

    return _PipelineIterator(iterate(), stats, stop)


def _pad_batch(batch: Batch, batch_size: int) -> Batch:
    """Pad a short eval batch to full size with valid=False rows."""
    b = batch.images.shape[0]
    pad = batch_size - b

    def pad0(x: np.ndarray) -> np.ndarray:
        widths = [(0, pad)] + [(0, 0)] * (x.ndim - 1)
        return np.pad(x, widths)

    return Batch(
        images=pad0(batch.images),
        gt_boxes=pad0(batch.gt_boxes),
        gt_labels=pad0(batch.gt_labels),
        gt_mask=pad0(batch.gt_mask),
        image_ids=pad0(batch.image_ids),
        scales=pad0(batch.scales),
        valid=np.concatenate([batch.valid, np.zeros(pad, dtype=bool)]),
    )
