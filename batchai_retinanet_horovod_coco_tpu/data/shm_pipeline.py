"""Multiprocess shared-memory input pipeline: the GIL-free producer.

Why this exists (PIPEBENCH.json round 5): the thread-pool producer in
``pipeline.py`` plateaus at ~2 workers because PIL's JPEG decode holds the
GIL (cv2's resize releases it, but decode dominates), capping a host at
~37 imgs/s — far below the ~67 imgs/s/chip the train step consumes.  Here
the decode/augment/resize fan-out runs in ``num_worker_procs`` WORKER
PROCESSES instead, each writing its decoded image directly into a
preallocated POSIX shared-memory ring buffer, so the only things crossing
the process boundary by pickling are a few ints and the (tiny) gt arrays —
never an image.

Architecture
------------
- One shared-memory **slab per bucket shape**: ``(slots, H, W, 3)`` uint8
  (float32 under ``host_normalize``).  Slots are a parent-managed free list;
  a worker writes example ``seq`` into its assigned slot and reports
  ``(seq, h, w, boxes, labels, scale)`` on the result queue.
- The **parent coordinator** (a thread, same shape as the thread-path
  producer) plans batches with the exact same deterministic
  ``batch_plans``/``example_rng`` helpers the thread path uses, assigns
  slots, and assembles finished batches IN SUBMISSION ORDER via the shared
  ``_assemble`` — so the two paths are bit-identical for a fixed seed.
- ``PipelineStats`` is tracked centrally at assembly (truncation is counted
  where the padding happens), so counters need no cross-process machinery.

Robustness contract (tested in tests/unit/test_shm_pipeline.py):
- a worker CRASH surfaces as a RuntimeError in the consumer within ~a
  second (liveness poll each pump iteration), after children are reaped and
  the shared memory unlinked;
- a worker WEDGE (alive but stuck) trips ``config.worker_timeout`` on the
  head-of-line batch — never a silent hang;
- ``close()`` is idempotent and reaps every child and /dev/shm segment;
  a ``weakref.finalize`` backstops leak-free teardown when the consumer
  drops the iterator without closing it.

Workers are ``spawn``ed by default: forking a parent that has initialized
JAX/XLA (thread pools, a possibly-live TPU client) is unsafe, and the
workers only need the data layer (numpy/PIL/cv2) — they never import jax.
"""

from __future__ import annotations

import os
import queue
import threading
import time
import traceback
import uuid
import weakref
from collections import deque
from multiprocessing import shared_memory

import numpy as np

from batchai_retinanet_horovod_coco_tpu.data.pipeline import (
    Batch,
    PipelineConfig,
    PipelineStats,
    _assemble,
    _pad_batch,
    batch_plans,
    example_rng,
    load_example,
    stop_gated_put,
)
from batchai_retinanet_horovod_coco_tpu.obs import trace, watchdog

_SENTINEL = object()
_SHM_PREFIX = "bretshm"  # distinctive: tests scan /dev/shm for leaks


class _StopRequested(Exception):
    """Internal: the consumer closed the pipeline; unwind the producer."""


def _attach_shm(name: str) -> shared_memory.SharedMemory:
    """Attach to an existing segment WITHOUT registering it with this
    process's resource tracker.

    The parent owns the segments (creates once, unlinks once).  Spawned
    children INHERIT the parent's resource-tracker process, so a child
    attach that registers (as pre-3.13 ``SharedMemory`` unconditionally
    does) plus the matching unregister-after-attach workaround races the
    parent's own unlink-time unregister — observed as KeyError noise in the
    shared tracker.  Python 3.13 has ``track=False`` for exactly this; on
    older versions the clean equivalent is to suppress the registration
    call itself for the duration of the attach.
    """
    try:
        return shared_memory.SharedMemory(name=name, track=False)  # py>=3.13
    except TypeError:
        pass
    from multiprocessing import resource_tracker

    orig_register = resource_tracker.register

    def _no_shm_register(rname, rtype):
        if rtype != "shared_memory":
            orig_register(rname, rtype)

    resource_tracker.register = _no_shm_register
    try:
        return shared_memory.SharedMemory(name=name)
    finally:
        resource_tracker.register = orig_register


def _worker_main(
    worker_id: int,
    dataset,
    config: PipelineConfig,
    train: bool,
    slabs: list[tuple[str, tuple[int, ...], str]],
    task_q,
    result_q,
    stop_evt,
) -> None:
    """Worker-process loop: task → decode/augment/resize → shm slot.

    Tasks are ``(seq, epoch, idx, bucket_id, slot)``; the heavy image bytes
    land in ``slabs[bucket_id][slot]`` and only the small result tuple is
    pickled back.  Any failure is reported on the result queue (with the
    traceback) before a hard exit, so the parent can re-raise it verbatim.
    """
    import signal

    signal.signal(signal.SIGINT, signal.SIG_IGN)  # parent owns Ctrl-C
    # Tracing self-enables iff the parent exported the obs env contract
    # before the spawn; the decode spans land in this process's own trace
    # file (exported on clean exit) and merge into the parent's timeline.
    # obs.trace never imports jax, preserving this worker's no-jax rule.
    tracing = trace.maybe_configure_from_env(f"shm-worker-{worker_id}")
    try:
        from batchai_retinanet_horovod_coco_tpu.data.transforms import cv2

        if cv2 is not None:
            # One core per worker: N workers already saturate N cores, and
            # cv2's own thread pool would only fight them for cycles.
            cv2.setNumThreads(1)
    except Exception:
        pass
    shms: list[shared_memory.SharedMemory] = []
    try:
        views = []
        for name, shape, dtype in slabs:
            shm = _attach_shm(name)
            shms.append(shm)
            views.append(np.ndarray(shape, dtype=np.dtype(dtype), buffer=shm.buf))
        while not stop_evt.is_set():
            try:
                task = task_q.get(timeout=0.2)
            except queue.Empty:
                continue
            if task is None:
                break
            seq, epoch, idx, bucket_id, slot = task
            record = dataset.records[idx]
            with trace.span("decode"):
                img, boxes, labels, scale = load_example(
                    dataset,
                    record,
                    config,
                    example_rng(config, train, epoch, idx),
                    config.buckets[bucket_id],
                )
            h, w = img.shape[:2]
            with trace.span("shm_write"):
                views[bucket_id][slot, :h, :w] = img
            result_q.put(("ok", seq, h, w, boxes, labels, scale))
        if tracing:
            trace.export()  # clean exit only; a crashed worker's trace is
            # forfeit (os._exit below), the parent's diagnosis carries on
    except BaseException:
        try:
            result_q.put(("err", worker_id, traceback.format_exc()))
            # Flush the queue's feeder thread BEFORE the hard exit, or the
            # error report can die in the buffer and the parent only sees
            # a generic "worker died" without the traceback.
            result_q.close()
            result_q.join_thread()
        except Exception:
            pass
        os._exit(1)
    finally:
        del views  # drop buffer exports before closing the mappings
        for shm in shms:
            try:
                shm.close()
            except BufferError:
                pass


def _finalize_pipeline(stop, mp_stop, procs, task_q, result_q, shms, views):
    """GC/close() teardown: also stops the coordinator thread.

    The producer's own exit path calls ``_cleanup_resources`` directly
    instead — it must NOT set ``stop``, because after an error it still has
    one exception to deliver through the (stop-gated) output queue.
    """
    stop.set()
    _cleanup_resources(mp_stop, procs, task_q, result_q, shms, views)


def _cleanup_resources(mp_stop, procs, task_q, result_q, shms, views) -> None:
    """Reap children and unlink shared memory.  Idempotent; never raises.

    Runs (first-come, all tolerated) from the producer's exit path, from
    ``close()``, and from the iterator's ``weakref.finalize`` backstop.
    """
    mp_stop.set()
    for _ in procs:
        try:
            task_q.put_nowait(None)
        except Exception:
            pass
    deadline = trace.monotonic_s() + 5.0
    for p in procs:
        try:
            p.join(timeout=max(0.1, deadline - trace.monotonic_s()))
        except Exception:
            pass
    for p in procs:
        try:
            if p.is_alive():
                p.terminate()
        except Exception:
            pass
    for p in procs:
        try:
            p.join(timeout=2.0)
            if p.is_alive():
                p.kill()
                p.join(timeout=2.0)
        except Exception:
            pass
    for q in (task_q, result_q):
        try:
            q.cancel_join_thread()
            q.close()
        except Exception:
            pass
    views.clear()  # release buffer exports so the mmaps can close
    for shm in shms:
        try:
            shm.close()
        except Exception:
            pass
        try:
            shm.unlink()
        except FileNotFoundError:
            pass
        except Exception:
            pass


class _ShmPipeline:
    """Iterator over batches produced by worker processes.

    Same surface as the thread path's ``_PipelineIterator``: iteration,
    live ``stats``, ``close()``.  Extra (for tests/tooling): ``processes``
    (the live ``multiprocessing.Process`` objects) and ``shm_names``.
    """

    def __init__(self, dataset, config: PipelineConfig, train: bool):
        import multiprocessing as mp

        if config.num_worker_procs <= 0:
            raise ValueError("build_shm_pipeline needs num_worker_procs > 0")
        self._config = config
        self._dataset = dataset
        self._train = train
        self.stats = PipelineStats()
        ctx = mp.get_context(config.mp_start_method)

        # Mirror the thread path's in-flight batch window so neither path
        # drains its workers at a batch boundary; +1 batch of slots covers
        # the batch currently being planned (its slots are allocated before
        # the batch joins the in-flight deque).
        bs = max(1, config.batch_size)
        self._max_inflight = max(
            2, -(-config.num_worker_procs // bs) + 1
        )
        self._slots_per_bucket = bs * (self._max_inflight + 1)
        dtype = np.float32 if config.host_normalize else np.uint8
        run_id = f"{_SHM_PREFIX}{os.getpid()}_{uuid.uuid4().hex[:6]}"
        self._shms: list[shared_memory.SharedMemory] = []
        self._views: list[np.ndarray] = []
        self._slab_spec: list[tuple[str, tuple[int, ...], str]] = []
        try:
            for k, (bh, bw) in enumerate(config.buckets):
                shape = (self._slots_per_bucket, bh, bw, 3)
                nbytes = int(np.prod(shape)) * np.dtype(dtype).itemsize
                shm = shared_memory.SharedMemory(
                    name=f"{run_id}_{k}", create=True, size=nbytes
                )
                self._shms.append(shm)
                self._views.append(
                    np.ndarray(shape, dtype=dtype, buffer=shm.buf)
                )
                self._slab_spec.append((shm.name, shape, np.dtype(dtype).str))
        except BaseException:
            # A partway create failure (undersized /dev/shm — Docker
            # defaults to 64 MB — raises ENOSPC on slab k) happens BEFORE
            # the finalizer below exists; without this, slabs 0..k-1 would
            # outlive the process in /dev/shm.
            self._views.clear()
            for shm in self._shms:
                try:
                    shm.close()
                except Exception:
                    pass
                try:
                    shm.unlink()
                except Exception:
                    pass
            raise
        self.shm_names = [s.name for s in self._shms]
        self._bucket_ids = {b: i for i, b in enumerate(config.buckets)}

        # lint: bounded-queues: in-flight tasks are bounded by the slot
        # tokens — the coordinator only submits while it holds a free shm
        # slot, so depth ≤ slots_per_bucket × len(buckets) by protocol.
        self._task_q = ctx.Queue()
        # lint: bounded-queues: one result per in-flight task; bounded by
        # the same slot-token protocol as the task queue above.
        self._result_q = ctx.Queue()
        self._mp_stop = ctx.Event()
        # watchdog-exempt (workers): decode workers heartbeat IMPLICITLY
        # through the result queue — the coordinator (registered in
        # _producer) beats a shm-pipe component per arriving result, so a
        # dead/wedged fleet stops that heartbeat within one task.
        self.processes = [
            ctx.Process(
                target=_worker_main,
                args=(
                    w, dataset, config, train, self._slab_spec,
                    self._task_q, self._result_q, self._mp_stop,
                ),
                daemon=True,
                name=f"shm-pipe-worker-{w}",
            )
            for w in range(config.num_worker_procs)
        ]

        # Producer-side state (all touched only by the coordinator thread).
        self._out: queue.Queue = queue.Queue(maxsize=max(1, config.prefetch))
        self._stop = threading.Event()
        self._free: list[deque] = [
            deque(range(self._slots_per_bucket)) for _ in config.buckets
        ]
        self._inflight: deque = deque()
        self._results: dict[int, tuple] = {}
        self._seq_slot: dict[int, tuple[int, int]] = {}
        self._next_seq = 0
        self._finished = False  # set once the stream terminally ended
        self._last_liveness = 0.0  # last worker-liveness poll (monotonic)

        # Backstop BEFORE any child starts: if a spawn fails halfway, the
        # half-built pipeline still reaps and unlinks at GC.
        self._finalizer = weakref.finalize(
            self,
            _finalize_pipeline,
            self._stop,
            self._mp_stop,
            self.processes,
            self._task_q,
            self._result_q,
            self._shms,
            self._views,
        )
        for p in self.processes:
            p.start()
        self._hb = None  # registered by the coordinator thread itself
        # watchdog: registers in _producer() at thread start.
        self._thread = threading.Thread(
            target=self._producer, daemon=True, name="shm-pipe-coordinator"
        )
        self._thread.start()

    # ---- consumer surface ------------------------------------------------

    def __iter__(self):
        return self

    def __next__(self) -> Batch:
        if self._finished:
            # Match generator semantics: once the stream ended (epoch
            # sentinel or a delivered exception), further next() calls
            # raise StopIteration instead of blocking on a dead queue.
            raise StopIteration
        item = self._out.get()
        if item is _SENTINEL:
            self._finished = True
            self.close()
            raise StopIteration
        if isinstance(item, BaseException):
            # Children are already reaped and shm unlinked (the producer
            # cleans up BEFORE delivering the exception); close() here just
            # stops the coordinator thread.
            self._finished = True
            self.close()
            raise item
        return item

    def close(self) -> None:
        """Stop the coordinator, reap all children, unlink all segments."""
        self._stop.set()
        if (
            self._thread.is_alive()
            and self._thread is not threading.current_thread()
        ):
            self._thread.join(timeout=10)
        self._finalizer()

    # ---- producer (coordinator thread) -----------------------------------

    def _put(self, item) -> bool:
        return stop_gated_put(self._out, item, self._stop)

    def _check_workers(self) -> None:
        self._last_liveness = trace.monotonic_s()
        for p in self.processes:
            if not p.is_alive():
                # Prefer the worker's own report: a worker that errored
                # queues a traceback then exits, and the liveness poll can
                # win the race against the queue's feeder thread.  Grace-
                # drain briefly before falling back to the generic verdict.
                grace = trace.monotonic_s() + 1.0
                while trace.monotonic_s() < grace:
                    try:
                        msg = self._result_q.get_nowait()
                    except queue.Empty:
                        time.sleep(0.05)
                        continue
                    if msg[0] == "err":
                        raise RuntimeError(
                            f"input-pipeline worker {msg[1]} failed:\n"
                            f"{msg[2]}"
                        )
                    _, seq, h, w, boxes, labels, scale = msg
                    self._results[seq] = (h, w, boxes, labels, scale)
                raise RuntimeError(
                    f"input-pipeline worker {p.name} (pid {p.pid}) died "
                    f"unexpectedly with exit code {p.exitcode}; the decode "
                    "fleet is no longer intact, aborting the run"
                )

    def _pump_until(self, cond) -> None:
        """Drain worker results until ``cond()`` holds.

        Raises on consumer stop, worker error, worker death, or when the
        condition makes no progress within ``config.worker_timeout`` —
        the bounded-stall guarantee (a wedged worker can stall the
        head-of-line batch forever; a timeout is the only way to surface
        an alive-but-stuck child).
        """
        deadline = trace.monotonic_s() + self._config.worker_timeout
        while not cond():
            if self._stop.is_set():
                raise _StopRequested
            # Liveness at a bounded cadence even under continuous result
            # flow: with one dead worker and N-1 healthy ones the result
            # queue can stay non-empty indefinitely, and an idle-poll-only
            # check would miss the death until the stream happened to
            # drain (observed as a 30s+ detection gap on a loaded box).
            if trace.monotonic_s() - self._last_liveness > 0.5:
                self._check_workers()
            try:
                msg = self._result_q.get(timeout=0.1)
            except queue.Empty:
                msg = None
            if msg is not None:
                if msg[0] == "err":
                    raise RuntimeError(
                        f"input-pipeline worker {msg[1]} failed:\n{msg[2]}"
                    )
                _, seq, h, w, boxes, labels, scale = msg
                self._results[seq] = (h, w, boxes, labels, scale)
                # Any arriving result IS progress: the timeout bounds a
                # STALL, not total head-batch latency (expensive decodes
                # trickling in steadily must never trip it).  The same
                # arrival is the worker fleet's implicit watchdog
                # heartbeat (workers never register themselves).
                if self._hb is not None:
                    self._hb.beat()
                deadline = trace.monotonic_s() + self._config.worker_timeout
                continue
            self._check_workers()
            if trace.monotonic_s() > deadline:
                raise RuntimeError(
                    "input pipeline stalled: no progress on the head batch "
                    f"within worker_timeout={self._config.worker_timeout}s "
                    f"({self._config.num_worker_procs} workers alive but "
                    "not delivering; a wedged worker or a pathologically "
                    "slow decode — raise PipelineConfig.worker_timeout if "
                    "the latter is expected)"
                )

    def _acquire_slot(self, bucket_id: int) -> int:
        while not self._free[bucket_id]:
            # Slots recycle at assembly; flushing the head batch is the
            # only way to mint free slots.  Deadlock-free: slots_per_bucket
            # > max_inflight * batch_size guarantees the head batch's tasks
            # are always fully submitted, and tasks are consumed FIFO.
            self._flush_head()
        return self._free[bucket_id].popleft()

    def _flush_head(self) -> None:
        bucket, bucket_id, seqs, ids, short = self._inflight[0]
        with trace.span("shm_head_wait"):
            self._pump_until(lambda: all(s in self._results for s in seqs))
        self._inflight.popleft()
        examples = []
        slots = []
        for s in seqs:
            h, w, boxes, labels, scale = self._results.pop(s)
            b_id, slot = self._seq_slot.pop(s)
            slots.append(slot)
            examples.append(
                (self._views[b_id][slot, :h, :w], boxes, labels, scale)
            )
        # _assemble copies the shm views into a fresh batch, so the slots
        # can recycle immediately and the consumer never aliases the ring.
        with trace.span("shm_assemble"):
            batch = _assemble(examples, ids, bucket, self._config, self.stats)
        self._free[bucket_id].extend(slots)
        if short:
            batch = _pad_batch(batch, self._config.batch_size)
        if trace.enabled():
            trace.counter("shm.out_qsize", self._out.qsize())
            trace.counter("shm.inflight_batches", len(self._inflight))
        if self._hb is not None:
            self._hb.idle()  # blocked on a full output queue = backpressure
        ok = self._put(batch)
        if self._hb is not None:
            self._hb.beat()
        if not ok:
            raise _StopRequested

    def _produce(self) -> None:
        config, train = self._config, self._train
        epoch = 0
        # Elastic-resume fast-forward: same plan-level skip as the thread
        # producer (data/pipeline.py) — bit-identical streams require the
        # two paths to skip identically.
        to_skip = config.skip_batches if train else 0
        while not self._stop.is_set():
            for bucket, chunk, ids, short in batch_plans(
                self._dataset, config, train, epoch
            ):
                if to_skip > 0:
                    to_skip -= 1
                    continue
                bucket_id = self._bucket_ids[bucket]
                seqs = []
                for i in chunk:
                    slot = self._acquire_slot(bucket_id)
                    seq = self._next_seq
                    self._next_seq += 1
                    self._seq_slot[seq] = (bucket_id, slot)
                    seqs.append(seq)
                    self._task_q.put((seq, epoch, int(i), bucket_id, slot))
                self._inflight.append((bucket, bucket_id, seqs, ids, short))
                while len(self._inflight) >= self._max_inflight:
                    self._flush_head()
            if not train:
                while self._inflight:
                    self._flush_head()
                self._put(_SENTINEL)
                return
            epoch += 1

    def _cleanup(self) -> None:
        _cleanup_resources(
            self._mp_stop, self.processes, self._task_q, self._result_q,
            self._shms, self._views,
        )

    def _producer(self) -> None:
        self._hb = watchdog.register(
            "shm-pipe-coordinator",
            # One heartbeat covers coordinator AND fleet: it beats on every
            # worker result (_pump_until) and every delivered batch
            # (_flush_head); details snapshot the queue/slot state a stall
            # diagnosis needs.
            details=lambda: {
                "out_qsize": self._out.qsize(),
                "inflight_batches": len(self._inflight),
                "pending_results": len(self._results),
                "workers_alive": sum(p.is_alive() for p in self.processes),
            },
        )
        try:
            self._produce()
        except _StopRequested:
            pass
        except BaseException as exc:
            # Clean up FIRST so that when the consumer sees the exception,
            # the children are already reaped and /dev/shm is already clean
            # (the consumer may be in a test that immediately checks both).
            # Direct _cleanup, NOT the finalizer: the finalizer would set
            # the stop flag, and the stop-gated _put below must still be
            # able to deliver this exception to a live consumer.
            self._cleanup()
            self._put(exc)
            return
        finally:
            self._hb.close()  # a closed pipeline must not look "stalled"
        self._cleanup()


def build_shm_pipeline(
    dataset, config: PipelineConfig, train: bool = True
) -> _ShmPipeline:
    """Multiprocess twin of ``pipeline.build_pipeline`` (its dispatch target
    when ``config.num_worker_procs > 0``) — same batches, same order, same
    bits; decoded by processes instead of GIL-bound threads."""
    return _ShmPipeline(dataset, config, train)
