"""Random geometric + photometric augmentation (host-side).

Parity target: keras-retinanet's ``utils/transform.py`` random affine
generator and ``utils/image.py`` visual effects (SURVEY.md M8,
``random_transform_group_entry``): a homogeneous 3x3 affine composed of
rotation, translation, shear, scaling, and axis flips — applied about the
image center, with the translation expressed as a fraction of the image size
— plus brightness/contrast/saturation jitter.  The reference enabled this
with its ``--random-transform`` flag; flip-only is the default recipe.

This runs on host CPU inside the data-loader workers (numpy + cv2/PIL), like
the reference; the TPU never sees it.  Boxes are transformed by mapping all
four corners and taking the axis-aligned bounding box, then clipped to the
image; boxes that degenerate (< 1px on a side) are dropped — the analogue of
the reference generator's invalid-annotation filtering.
"""

from __future__ import annotations

import dataclasses
import warnings

import numpy as np

try:
    import cv2
except ImportError:  # pragma: no cover - exercised on cv2-less machines
    cv2 = None
    warnings.warn(
        "opencv not importable: falling back to PIL/numpy image ops, which "
        "are slower AND not pixel-identical to the cv2 paths — do not mix "
        "cv2 and non-cv2 hosts in one data-parallel run",
        RuntimeWarning,
    )


@dataclasses.dataclass(frozen=True)
class TransformConfig:
    """Ranges for the random affine + photometric jitter.

    Defaults mirror the reference's ``random_transform_generator`` ranges:
    rotation/shear in radians, translation as a fraction of the image size,
    scaling as multiplicative factors.
    """

    rotation: tuple[float, float] = (-0.1, 0.1)
    translation: tuple[float, float] = (-0.1, 0.1)
    shear: tuple[float, float] = (-0.1, 0.1)
    scaling: tuple[float, float] = (0.9, 1.1)
    flip_x_prob: float = 0.5
    flip_y_prob: float = 0.0
    # Photometric ("visual effect") jitter; identity ranges disable a term.
    brightness: tuple[float, float] = (-0.1, 0.1)  # additive, fraction of 255
    contrast: tuple[float, float] = (0.9, 1.1)
    saturation: tuple[float, float] = (0.95, 1.05)


def _rotation(theta: float) -> np.ndarray:
    c, s = np.cos(theta), np.sin(theta)
    return np.array([[c, -s, 0.0], [s, c, 0.0], [0.0, 0.0, 1.0]])


def _translation(tx: float, ty: float) -> np.ndarray:
    return np.array([[1.0, 0.0, tx], [0.0, 1.0, ty], [0.0, 0.0, 1.0]])


def _shear(angle: float) -> np.ndarray:
    return np.array(
        [[1.0, -np.sin(angle), 0.0], [0.0, np.cos(angle), 0.0], [0.0, 0.0, 1.0]]
    )


def _scaling(sx: float, sy: float) -> np.ndarray:
    return np.diag([sx, sy, 1.0])


def random_transform_matrix(
    config: TransformConfig, rng: np.random.Generator, height: int, width: int
) -> np.ndarray:
    """Sample one 3x3 affine in PIXEL coordinates, centered on the image.

    Composition order matches the reference: rotation @ translation @ shear @
    scaling @ flip, with translation scaled by (width, height) and the whole
    transform conjugated so its origin is the image center.
    """
    u = lambda lo_hi: float(rng.uniform(*lo_hi))  # noqa: E731
    m = _rotation(u(config.rotation))
    m = m @ _translation(
        u(config.translation) * width, u(config.translation) * height
    )
    m = m @ _shear(u(config.shear))
    m = m @ _scaling(u(config.scaling), u(config.scaling))
    flip_x = rng.random() < config.flip_x_prob
    flip_y = rng.random() < config.flip_y_prob
    m = m @ _scaling(-1.0 if flip_x else 1.0, -1.0 if flip_y else 1.0)
    center = _translation(width / 2.0, height / 2.0)
    return center @ m @ np.linalg.inv(center)


def warp_image(image: np.ndarray, matrix: np.ndarray) -> np.ndarray:
    """Apply a 3x3 affine to a uint8 HWC image, same output size."""
    h, w = image.shape[:2]
    if cv2 is not None:
        return cv2.warpAffine(
            image,
            matrix[:2].astype(np.float64),
            (w, h),
            flags=cv2.INTER_LINEAR,
            borderMode=cv2.BORDER_CONSTANT,
        )
    from PIL import Image

    inv = np.linalg.inv(matrix)  # PIL wants the output→input mapping
    coeffs = inv[:2].reshape(-1).tolist()
    return np.asarray(
        Image.fromarray(image).transform(
            (w, h), Image.AFFINE, coeffs, resample=Image.BILINEAR
        )
    )


def transform_boxes(
    boxes: np.ndarray, matrix: np.ndarray, height: int, width: int
) -> tuple[np.ndarray, np.ndarray]:
    """Map corner boxes through an affine; AABB of the 4 corners, clipped.

    Returns (boxes, keep) where ``keep`` marks boxes still ≥1px on both
    sides after clipping.
    """
    if len(boxes) == 0:
        return boxes, np.zeros((0,), dtype=bool)
    x1, y1, x2, y2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    corners = np.stack(
        [
            np.stack([x1, y1], axis=1),
            np.stack([x2, y1], axis=1),
            np.stack([x1, y2], axis=1),
            np.stack([x2, y2], axis=1),
        ],
        axis=1,
    )  # (N, 4, 2)
    ones = np.ones((*corners.shape[:2], 1))
    mapped = np.concatenate([corners, ones], axis=2) @ matrix.T  # (N, 4, 3)
    xs, ys = mapped[..., 0], mapped[..., 1]
    out = np.stack(
        [xs.min(axis=1), ys.min(axis=1), xs.max(axis=1), ys.max(axis=1)], axis=1
    ).astype(np.float32)
    out[:, 0::2] = np.clip(out[:, 0::2], 0, width)
    out[:, 1::2] = np.clip(out[:, 1::2], 0, height)
    keep = ((out[:, 2] - out[:, 0]) >= 1.0) & ((out[:, 3] - out[:, 1]) >= 1.0)
    return out, keep


def apply_visual_effects(
    image: np.ndarray, config: TransformConfig, rng: np.random.Generator
) -> np.ndarray:
    """Brightness/contrast/saturation jitter on a uint8 HWC image.

    Algebraically fused: brightness (+b), contrast about the global mean m
    (c·x + (m+b)(1−c) after brightness), and saturation about the per-pixel
    gray (s·x + (1−s)·gray) compose into ONE linear pass
    ``s·c·x + (1−s)·c·gray(x) + k`` — this function is the data-loader's
    hottest op (profiled at ~54 ms/image at 640px in the naive
    one-op-per-effect form, float64 means included; fused ~7 ms).
    """
    b = float(rng.uniform(*config.brightness)) * 255.0
    c = float(rng.uniform(*config.contrast))
    s = float(rng.uniform(*config.saturation))
    a1 = s * c
    a2 = (1.0 - s) * c / 3.0  # gray = (r+g+b)/3 folded into the mix matrix
    if cv2 is not None:
        m = float(sum(cv2.mean(image)[:3]) / 3.0) + b
        k = c * b + m * (1.0 - c)
        # One saturating SIMD pass: out = M @ [r g b 1]^T per pixel.
        mix = np.full((3, 4), a2, dtype=np.float64)
        mix[:, :3] += np.eye(3) * a1
        mix[:, 3] = k
        return cv2.transform(image, mix)
    m = float(image.mean(dtype=np.float32)) + b
    k = c * b + m * (1.0 - c)
    gray = image.mean(axis=2, keepdims=True, dtype=np.float32)
    out = image.astype(np.float32)
    out *= a1
    out += gray * ((1.0 - s) * c)
    out += k
    return np.clip(out, 0, 255, out=out).astype(np.uint8)


def apply_random_transform(
    image: np.ndarray,
    boxes: np.ndarray,
    labels: np.ndarray,
    config: TransformConfig,
    rng: np.random.Generator,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """One full augmentation draw: affine warp + box remap + photometric."""
    h, w = image.shape[:2]
    matrix = random_transform_matrix(config, rng, h, w)
    image = warp_image(image, matrix)
    boxes, keep = transform_boxes(boxes, matrix, h, w)
    image = apply_visual_effects(image, config, rng)
    return image, boxes[keep], labels[keep]
