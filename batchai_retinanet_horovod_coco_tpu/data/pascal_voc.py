"""Pascal VOC detection dataset (keras-retinanet PascalVocGenerator parity).

The reference library's third data source (alongside COCO and CSV):
``preprocessing/pascal_voc.py``, driven by the ``pascal`` subcommand of
``bin/train.py``.  Standard VOCdevkit layout:

    <root>/ImageSets/Main/<split>.txt    image ids, one per line
    <root>/Annotations/<id>.xml          objects: name + bndbox (1-based)
    <root>/JPEGImages/<id>.jpg

Semantics mirrored from the reference:

- the 20 canonical VOC classes map to contiguous labels 0..19 (same order);
- ``bndbox`` coordinates are 1-based → the reference's
  ``__parse_annotation`` subtracts 1 from all four, and so does this parser;
- ``difficult`` objects are kept but routed to the record's ignore set
  (``crowd_*`` fields — the COCOeval oracle treats those as ignore regions,
  matching VOC eval's treatment of difficult boxes; pass
  ``skip_difficult=True`` to drop them entirely, the reference's flag);
- image sizes come from the XML ``<size>`` block when present, else the
  image header.
"""

from __future__ import annotations

import os
import xml.etree.ElementTree as ET

import numpy as np

from batchai_retinanet_horovod_coco_tpu.data.coco import ImageRecord

VOC_CLASSES = (
    "aeroplane", "bicycle", "bird", "boat", "bottle", "bus", "car", "cat",
    "chair", "cow", "diningtable", "dog", "horse", "motorbike", "person",
    "pottedplant", "sheep", "sofa", "train", "tvmonitor",
)


def _parse_box(obj: ET.Element, image_id: str) -> tuple[np.ndarray, str, bool]:
    name_el = obj.find("name")
    box_el = obj.find("bndbox")
    if name_el is None or box_el is None:
        raise ValueError(f"{image_id}: malformed <object> (missing name/bndbox)")

    def coord(tag: str) -> float:
        el = box_el.find(tag)
        if el is None or el.text is None:
            raise ValueError(f"{image_id}: missing <{tag}>")
        return float(el.text)

    # VOC coords are 1-based; the reference generator subtracts 1 from ALL
    # FOUR coordinates (keras-retinanet __parse_annotation), so parity keeps
    # that convention (boxes are 1px narrower than the strict
    # inclusive→exclusive conversion would give; matching the reference
    # outweighs the devkit pedantry).
    box = np.array(
        [
            coord("xmin") - 1,
            coord("ymin") - 1,
            coord("xmax") - 1,
            coord("ymax") - 1,
        ],
        dtype=np.float32,
    )
    difficult_el = obj.find("difficult")
    difficult = bool(int(difficult_el.text)) if (
        difficult_el is not None and difficult_el.text
    ) else False
    return box, (name_el.text or "").strip(), difficult


class PascalVocDataset:
    """VOCdevkit dataset exposing the ``CocoDataset`` duck-type interface."""

    def __init__(
        self,
        root: str,
        split: str = "train",
        classes: tuple[str, ...] = VOC_CLASSES,
        skip_difficult: bool = False,
        keep_empty: bool = False,
    ):
        self.root = root
        self.image_dir = os.path.join(root, "JPEGImages")
        self.class_names = list(classes)
        name_to_label = {n: i for i, n in enumerate(self.class_names)}
        self.cat_id_to_label = {i: i for i in range(len(self.class_names))}
        self.label_to_cat_id = dict(self.cat_id_to_label)

        split_file = os.path.join(root, "ImageSets", "Main", f"{split}.txt")
        with open(split_file) as f:
            ids = [line.split(None, 1)[0] for line in f if line.strip()]

        self.records: list[ImageRecord] = []
        for image_id, vid in enumerate(ids):
            xml_path = os.path.join(root, "Annotations", f"{vid}.xml")
            tree = ET.parse(xml_path)
            troot = tree.getroot()

            fname_el = troot.find("filename")
            file_name = (
                fname_el.text.strip()
                if fname_el is not None and fname_el.text
                else f"{vid}.jpg"
            )
            size = troot.find("size")
            w_el = size.find("width") if size is not None else None
            h_el = size.find("height") if size is not None else None
            if (
                w_el is not None and w_el.text
                and h_el is not None and h_el.text
            ):
                width = int(float(w_el.text))
                height = int(float(h_el.text))
            else:
                from PIL import Image

                with Image.open(os.path.join(self.image_dir, file_name)) as im:
                    width, height = im.size

            boxes, labels, ign_boxes, ign_labels = [], [], [], []
            for obj in troot.iter("object"):
                box, name, difficult = _parse_box(obj, vid)
                if name not in name_to_label:
                    raise ValueError(f"{vid}: unknown class {name!r}")
                if difficult:
                    if not skip_difficult:
                        ign_boxes.append(box)
                        ign_labels.append(name_to_label[name])
                    continue
                boxes.append(box)
                labels.append(name_to_label[name])

            if not boxes and not keep_empty:
                continue

            def pack(bs, ls):
                b = (
                    np.stack(bs).astype(np.float32)
                    if bs
                    else np.zeros((0, 4), np.float32)
                )
                l = np.asarray(ls, np.int32)
                areas = (
                    (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
                ).astype(np.float32)
                return b, l, areas

            b, l, a = pack(boxes, labels)
            ib, il, ia = pack(ign_boxes, ign_labels)
            self.records.append(
                ImageRecord(
                    image_id=image_id,
                    file_name=file_name,
                    width=width,
                    height=height,
                    boxes=b,
                    labels=l,
                    areas=a,
                    # Difficult objects ride the ignore channel: the COCO
                    # oracle marks crowd matches neither TP nor FP, VOC
                    # eval's difficult treatment.
                    crowd_boxes=ib,
                    crowd_labels=il,
                    crowd_areas=ia,
                )
            )

    @property
    def num_classes(self) -> int:
        return len(self.class_names)

    def __len__(self) -> int:
        return len(self.records)

    def image_path(self, record: ImageRecord) -> str:
        return os.path.join(self.image_dir, record.file_name)
