"""Background-thread prefetch: the ONE overlap idiom train and eval share.

PR 1 built double-buffered device prefetch for the train loop
(``train/loop.py``): a background thread pulls host batches and enqueues
their host→device DMA a bounded number of steps ahead, so step k's compute
overlaps batch k+1's transfer AND the host side of producing it (pipeline
queue wait, batch assembly, the ``device_put`` dispatch itself).  The eval
fast path (ISSUE 2) needs exactly the same machinery with a different
per-item transfer, so the thread/queue/stop/error skeleton lives here once
— ``prefetch_map`` — and both loops supply only their transfer function.

Error contract (same as the shm pipeline's, data/shm_pipeline.py): an
exception in the producer thread — including one raised by the underlying
batch iterable, e.g. a crashed decode worker — is re-raised in the
consumer; ``close()`` (generator close) stops the thread promptly even
when the bounded queue is full (every put is stop-gated).
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterable, Iterator, TypeVar

from batchai_retinanet_horovod_coco_tpu.data.pipeline import stop_gated_put
from batchai_retinanet_horovod_coco_tpu.obs import trace, watchdog

_T = TypeVar("_T")
_U = TypeVar("_U")


def prefetch_map(
    items: Iterable[_T],
    transfer: Callable[[_T], _U],
    depth: int = 2,
    thread_name: str = "prefetch-map",
) -> Iterator[_U]:
    """Yield ``transfer(item)`` with a background thread running up to
    ``depth`` items ahead of the consumer.

    ``transfer`` runs IN THE PRODUCER THREAD — for device prefetch it calls
    ``jax.device_put``, which enqueues the host→device DMA there, off the
    consumer's critical path.  ``depth=2`` is classic double buffering;
    ``depth <= 0`` degrades to a synchronous in-line map (debugging).

    The returned generator's ``close()`` stops the thread deterministically;
    exceptions from ``items`` or ``transfer`` re-raise here.
    """
    if depth <= 0:
        for item in items:
            yield transfer(item)
        return

    buf: queue.Queue = queue.Queue(maxsize=depth)
    stop = threading.Event()
    end = object()  # stream-exhausted sentinel

    def _enqueue(item) -> bool:
        return stop_gated_put(buf, item, stop)

    def feeder() -> None:
        # Observability (obs/): every produced item is a heartbeat and a
        # span on this thread's trace track; the queue depth is a counter.
        # ``idle()`` before the bounded put — blocking on a full queue is
        # backpressure from a busy consumer, not a stall.
        hb = watchdog.register(
            thread_name, details=lambda: {"qsize": buf.qsize(), "depth": depth}
        )
        try:
            for item in items:
                with trace.span(thread_name):
                    staged = transfer(item)
                hb.beat()
                hb.idle()
                if not _enqueue(staged):
                    return
                hb.beat()
                if trace.enabled():
                    trace.counter(f"{thread_name}.qsize", buf.qsize())
                if stop.is_set():
                    return
            hb.idle()  # sentinel delivery blocks on the same backpressure
            _enqueue(end)
        except BaseException as exc:  # propagate to the consumer
            hb.idle()
            _enqueue(exc)
        finally:
            hb.close()

    # watchdog: registers in feeder() at thread start.
    thread = threading.Thread(target=feeder, daemon=True, name=thread_name)
    thread.start()
    try:
        while True:
            item = buf.get()
            if item is end:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()
