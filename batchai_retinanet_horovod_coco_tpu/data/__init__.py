"""Data layer: COCO annotation parsing, synthetic datasets, input pipeline.

Capability parity with the reference's data path (SURVEY.md M8/M9:
keras-retinanet ``preprocessing/coco.py`` + ``preprocessing/generator.py``),
redesigned for TPU:

- annotations are parsed with a small self-contained JSON reader (this
  environment has no pycocotools; SURVEY.md §7);
- images are resized into a SMALL SET OF STATIC SHAPE BUCKETS instead of
  per-batch dynamic padding — XLA compiles one program per bucket
  (SURVEY.md §7.3 hard part 1);
- anchor targets are NOT computed here: the host ships only images + padded
  gt boxes, and target assignment runs on device inside the jit'd step
  (BASELINE.json:5), unlike the reference's CPU loader-thread hot loop
  (SURVEY.md call stack 3.3);
- two interchangeable producers behind one ``build_pipeline`` entrypoint:
  an in-process thread pool (default; pytest/low-resource) and a
  multiprocess shared-memory ring buffer (``num_worker_procs > 0``,
  ``shm_pipeline.py``) that clears the GIL decode ceiling — bit-identical
  batches for a fixed seed either way.
"""

from batchai_retinanet_horovod_coco_tpu.data.coco import CocoDataset, ImageRecord
from batchai_retinanet_horovod_coco_tpu.data.csv import CsvDataset
from batchai_retinanet_horovod_coco_tpu.data.pascal_voc import (
    VOC_CLASSES,
    PascalVocDataset,
)
from batchai_retinanet_horovod_coco_tpu.data.pipeline import (
    Batch,
    PipelineConfig,
    PipelineStats,
    build_pipeline,
    resolve_max_gt,
)
from batchai_retinanet_horovod_coco_tpu.data.synthetic import make_synthetic_coco
from batchai_retinanet_horovod_coco_tpu.data.transforms import TransformConfig

__all__ = [
    "Batch",
    "CocoDataset",
    "CsvDataset",
    "ImageRecord",
    "PascalVocDataset",
    "PipelineConfig",
    "PipelineStats",
    "VOC_CLASSES",
    "TransformConfig",
    "build_pipeline",
    "resolve_max_gt",
    "make_synthetic_coco",
]
