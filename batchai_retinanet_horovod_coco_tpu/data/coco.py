"""COCO instances-JSON parsing without pycocotools.

Parity target: keras-retinanet's ``CocoGenerator`` annotation handling
(SURVEY.md M9): load instances_*.json, map the sparse COCO category ids onto
contiguous labels 0..K-1 (sorted by category id, the pycocotools convention),
and expose per-image boxes/labels.  Boxes are converted from COCO ``[x, y, w,
h]`` to corner ``[x1, y1, x2, y2]`` once at load time.

Crowd annotations (``iscrowd=1``) are dropped for training, matching the
reference generator's default behavior.
"""

from __future__ import annotations

import dataclasses
import json
import os

import numpy as np


@dataclasses.dataclass
class ImageRecord:
    image_id: int
    file_name: str
    width: int
    height: int
    boxes: np.ndarray  # (N, 4) float32 corner boxes
    labels: np.ndarray  # (N,) int32 contiguous labels


class CocoDataset:
    """In-memory index of a COCO-format detection dataset."""

    def __init__(
        self,
        annotation_file: str,
        image_dir: str | None = None,
        include_crowd: bool = False,
        keep_empty: bool = False,
    ):
        with open(annotation_file) as f:
            blob = json.load(f)

        self.image_dir = image_dir or os.path.dirname(annotation_file)
        categories = sorted(blob.get("categories", []), key=lambda c: c["id"])
        self.cat_id_to_label = {c["id"]: i for i, c in enumerate(categories)}
        self.label_to_cat_id = {i: c["id"] for i, c in enumerate(categories)}
        self.class_names = [c["name"] for c in categories]

        per_image: dict[int, list[dict]] = {}
        for ann in blob.get("annotations", []):
            if not include_crowd and ann.get("iscrowd", 0):
                continue
            per_image.setdefault(ann["image_id"], []).append(ann)

        self.records: list[ImageRecord] = []
        for img in blob.get("images", []):
            anns = per_image.get(img["id"], [])
            boxes = np.zeros((len(anns), 4), dtype=np.float32)
            labels = np.zeros((len(anns),), dtype=np.int32)
            for i, ann in enumerate(anns):
                x, y, w, h = ann["bbox"]
                boxes[i] = [x, y, x + w, y + h]
                labels[i] = self.cat_id_to_label[ann["category_id"]]
            if len(anns) == 0 and not keep_empty:
                continue
            self.records.append(
                ImageRecord(
                    image_id=img["id"],
                    file_name=img["file_name"],
                    width=img["width"],
                    height=img["height"],
                    boxes=boxes,
                    labels=labels,
                )
            )

    @property
    def num_classes(self) -> int:
        return len(self.class_names)

    def __len__(self) -> int:
        return len(self.records)

    def image_path(self, record: ImageRecord) -> str:
        return os.path.join(self.image_dir, record.file_name)
