"""COCO instances-JSON parsing without pycocotools.

Parity target: keras-retinanet's ``CocoGenerator`` annotation handling
(SURVEY.md M9): load instances_*.json, map the sparse COCO category ids onto
contiguous labels 0..K-1 (sorted by category id, the pycocotools convention),
and expose per-image boxes/labels.  Boxes are converted from COCO ``[x, y, w,
h]`` to corner ``[x1, y1, x2, y2]`` once at load time.

Crowd annotations (``iscrowd=1``) are excluded from training boxes — matching
the reference generator's default — but are kept on the record separately so
evaluation can mark them ignore, exactly as pycocotools' COCOeval does
(detections matching a crowd region are neither TP nor FP).  Per-annotation
``area`` (segmentation area on real COCO) is preserved for COCOeval's
area-range bucketing, which uses it rather than the bbox area.
"""

from __future__ import annotations

import dataclasses
import json
import os

import numpy as np


@dataclasses.dataclass
class ImageRecord:
    image_id: int
    file_name: str
    width: int
    height: int
    boxes: np.ndarray  # (N, 4) float32 corner boxes (non-crowd)
    labels: np.ndarray  # (N,) int32 contiguous labels
    areas: np.ndarray  # (N,) float32 annotation areas (COCOeval bucketing)
    crowd_boxes: np.ndarray  # (C, 4) float32 corner boxes (iscrowd=1)
    crowd_labels: np.ndarray  # (C,) int32
    crowd_areas: np.ndarray  # (C,) float32


class CocoDataset:
    """In-memory index of a COCO-format detection dataset."""

    def __init__(
        self,
        annotation_file: str,
        image_dir: str | None = None,
        keep_empty: bool = False,
    ):
        with open(annotation_file) as f:
            blob = json.load(f)

        self.image_dir = image_dir or os.path.dirname(annotation_file)
        categories = sorted(blob.get("categories", []), key=lambda c: c["id"])
        self.cat_id_to_label = {c["id"]: i for i, c in enumerate(categories)}
        self.label_to_cat_id = {i: c["id"] for i, c in enumerate(categories)}
        self.class_names = [c["name"] for c in categories]

        per_image: dict[int, list[dict]] = {}
        for ann in blob.get("annotations", []):
            per_image.setdefault(ann["image_id"], []).append(ann)

        self.records: list[ImageRecord] = []
        for img in blob.get("images", []):
            anns = per_image.get(img["id"], [])
            normal = [a for a in anns if not a.get("iscrowd", 0)]
            crowd = [a for a in anns if a.get("iscrowd", 0)]
            if not normal and not keep_empty:
                continue
            self.records.append(
                ImageRecord(
                    image_id=img["id"],
                    file_name=img["file_name"],
                    width=img["width"],
                    height=img["height"],
                    **self._pack(normal, prefix=""),
                    **self._pack(crowd, prefix="crowd_"),
                )
            )

    def _pack(self, anns: list[dict], prefix: str) -> dict[str, np.ndarray]:
        boxes = np.zeros((len(anns), 4), dtype=np.float32)
        labels = np.zeros((len(anns),), dtype=np.int32)
        areas = np.zeros((len(anns),), dtype=np.float32)
        for i, ann in enumerate(anns):
            x, y, w, h = ann["bbox"]
            boxes[i] = [x, y, x + w, y + h]
            labels[i] = self.cat_id_to_label[ann["category_id"]]
            areas[i] = ann.get("area", w * h)
        return {
            f"{prefix}boxes": boxes,
            f"{prefix}labels": labels,
            f"{prefix}areas": areas,
        }

    @property
    def num_classes(self) -> int:
        return len(self.class_names)

    def __len__(self) -> int:
        return len(self.records)

    def image_path(self, record: ImageRecord) -> str:
        return os.path.join(self.image_dir, record.file_name)
