"""CSV-format detection dataset (keras-retinanet CSVGenerator parity).

The reference library ships a second, COCO-independent data source — the
``CSVGenerator`` (keras_retinanet/preprocessing/csv_generator.py, exercised by
tests/preprocessing in SURVEY.md §4) — consuming two plain CSV files:

  annotations.csv   one row per annotation:  path,x1,y1,x2,y2,class_name
                    an image with no annotations is listed as:  path,,,,,
  classes.csv       one row per class:       class_name,id   (ids 0..K-1)

This module parses that exact format into the same ``ImageRecord`` stream the
COCO dataset produces, so the whole downstream stack (bucketed pipeline,
on-device target assignment, COCO-semantics mAP oracle) works unchanged on
custom CSV datasets.  Validation mirrors the reference's behavior: malformed
rows, non-numeric or inverted coordinates, and unknown/duplicate classes all
raise ``ValueError`` with the offending line number.

Image sizes are read from the file headers at index time (PIL reads only the
header, no pixel decode) — the pipeline needs them up front for static bucket
selection, where the reference read them lazily per epoch.
"""

from __future__ import annotations

import csv as _csv
import math
import os

import numpy as np

from batchai_retinanet_horovod_coco_tpu.data.coco import ImageRecord

_EMPTY4 = np.zeros((0, 4), dtype=np.float32)
_EMPTY1 = np.zeros((0,), dtype=np.int32)
_EMPTY1F = np.zeros((0,), dtype=np.float32)


def _parse_num(value: str, what: str, line: int) -> float:
    # Python numeric literals allow digit-group underscores ('1_0' == 10);
    # a CSV containing one is a typo, not a number — reject it.
    if "_" in value:
        raise ValueError(f"line {line}: malformed {what}: {value!r}")
    try:
        parsed = float(value)
    except ValueError:
        raise ValueError(f"line {line}: malformed {what}: {value!r}") from None
    if not math.isfinite(parsed):
        raise ValueError(f"line {line}: malformed {what}: {value!r}")
    return parsed


def _parse_int(value: str, what: str, line: int) -> int:
    # isdecimal, not isdigit: digit-but-not-decimal characters ('²') pass
    # isdigit but are rejected by int().
    if not value.strip().isdecimal():
        raise ValueError(f"line {line}: malformed {what}: {value!r}")
    return int(value)


def read_classes(path: str) -> dict[str, int]:
    """Parse classes.csv → {name: id}; ids must be exactly 0..K-1."""
    mapping: dict[str, int] = {}
    with open(path, newline="") as f:
        reader = _csv.reader(f)
        for row in reader:
            # reader.line_num is the physical file line, correct even when a
            # quoted field spans multiple lines (record index would drift).
            line = reader.line_num
            if not row:
                continue
            if len(row) != 2:
                raise ValueError(
                    f"line {line}: expected 'class_name,id', got {row!r}"
                )
            name, raw_id = row
            if name in mapping:
                raise ValueError(f"line {line}: duplicate class name {name!r}")
            class_id = _parse_int(raw_id, "class id", line)
            if class_id in mapping.values():
                raise ValueError(f"line {line}: duplicate class id {class_id}")
            mapping[name] = class_id
    ids = sorted(mapping.values())
    if ids != list(range(len(ids))):
        raise ValueError(
            f"class ids must be contiguous 0..{len(ids) - 1}, got {ids}"
        )
    return mapping


class CsvDataset:
    """CSV-format dataset exposing the ``CocoDataset`` interface.

    Duck-type contract used downstream (data/pipeline.py, evaluate/detect.py):
    ``records`` (list of ImageRecord), ``num_classes``, ``class_names``,
    ``label_to_cat_id``/``cat_id_to_label`` (identity here — CSV class ids ARE
    the contiguous labels), and ``image_path``.
    """

    def __init__(
        self,
        annotation_file: str,
        classes_file: str,
        image_dir: str | None = None,
        keep_empty: bool = False,
    ):
        self.image_dir = image_dir or os.path.dirname(annotation_file)
        name_to_id = read_classes(classes_file)
        self.class_names = [
            name for name, _ in sorted(name_to_id.items(), key=lambda kv: kv[1])
        ]
        self.cat_id_to_label = {i: i for i in range(len(self.class_names))}
        self.label_to_cat_id = dict(self.cat_id_to_label)

        per_image: dict[str, list[tuple[np.ndarray, int]]] = {}
        order: list[str] = []
        with open(annotation_file, newline="") as f:
            reader = _csv.reader(f)
            for row in reader:
                line = reader.line_num  # physical line, not record index
                if not row:
                    continue
                if len(row) != 6:
                    raise ValueError(
                        f"line {line}: expected "
                        f"'path,x1,y1,x2,y2,class_name', got {row!r}"
                    )
                path, x1, y1, x2, y2, cls = row
                if path not in per_image:
                    per_image[path] = []
                    order.append(path)
                if (x1, y1, x2, y2, cls) == ("", "", "", "", ""):
                    continue  # explicit empty-image row
                box = np.array(
                    [
                        _parse_num(x1, "x1", line),
                        _parse_num(y1, "y1", line),
                        _parse_num(x2, "x2", line),
                        _parse_num(y2, "y2", line),
                    ],
                    dtype=np.float32,
                )
                if box[2] <= box[0]:
                    raise ValueError(
                        f"line {line}: x2 ({x2}) must be > x1 ({x1})"
                    )
                if box[3] <= box[1]:
                    raise ValueError(
                        f"line {line}: y2 ({y2}) must be > y1 ({y1})"
                    )
                if cls not in name_to_id:
                    raise ValueError(f"line {line}: unknown class {cls!r}")
                per_image[path].append((box, name_to_id[cls]))

        self.records: list[ImageRecord] = []
        for image_id, path in enumerate(order):
            anns = per_image[path]
            if not anns and not keep_empty:
                continue
            width, height = self._image_size(os.path.join(self.image_dir, path))
            if anns:
                boxes = np.stack([b for b, _ in anns]).astype(np.float32)
                labels = np.array([l for _, l in anns], dtype=np.int32)
                areas = ((boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1]))
                areas = areas.astype(np.float32)
            else:
                boxes, labels, areas = _EMPTY4, _EMPTY1, _EMPTY1F
            self.records.append(
                ImageRecord(
                    image_id=image_id,
                    file_name=path,
                    width=width,
                    height=height,
                    boxes=boxes,
                    labels=labels,
                    areas=areas,
                    crowd_boxes=_EMPTY4,
                    crowd_labels=_EMPTY1,
                    crowd_areas=_EMPTY1F,
                )
            )

    @staticmethod
    def _image_size(path: str) -> tuple[int, int]:
        from PIL import Image

        with Image.open(path) as im:  # header-only; no pixel decode
            return im.size  # (width, height)

    @property
    def num_classes(self) -> int:
        return len(self.class_names)

    def __len__(self) -> int:
        return len(self.records)

    def image_path(self, record: ImageRecord) -> str:
        return os.path.join(self.image_dir, record.file_name)
