"""Synthetic COCO-format datasets for tests, overfit runs, and benchmarks.

The reference validated against real COCO mounted from blob storage
(SURVEY.md W2); this air-gapped environment has no COCO, so we generate a
deterministic synthetic detection dataset — colored axis-aligned rectangles
on noise backgrounds, with class identity encoded in the rectangle's color —
written as real JPEG files + instances.json so the FULL pipeline (JPEG
decode, resize, bucketing, eval-JSON round trip) is exercised end to end.
An overfit run on this dataset is the capability analogue of the reference's
COCO-mini config (BASELINE.json configs[1]).
"""

from __future__ import annotations

import json
import os

import numpy as np

# A fixed palette: class k gets a distinct hue so the task is learnable.
_PALETTE = [
    (220, 40, 40),
    (40, 220, 40),
    (40, 40, 220),
    (220, 220, 40),
    (220, 40, 220),
    (40, 220, 220),
    (240, 140, 20),
    (140, 20, 240),
]


def make_synthetic_coco(
    root: str,
    num_images: int = 64,
    num_classes: int = 3,
    image_size: tuple[int, int] = (256, 256),
    max_objects: int = 4,
    seed: int = 0,
    split: str = "train",
) -> str:
    """Write a synthetic COCO dataset under ``root``; returns annotation path."""
    from PIL import Image

    assert num_classes <= len(_PALETTE)
    rng = np.random.default_rng(seed)
    img_dir = os.path.join(root, split)
    os.makedirs(img_dir, exist_ok=True)

    images, annotations = [], []
    ann_id = 1
    h, w = image_size
    for image_id in range(1, num_images + 1):
        canvas = rng.integers(90, 120, size=(h, w, 3), dtype=np.uint8)
        n_obj = int(rng.integers(1, max_objects + 1))
        for _ in range(n_obj):
            bw = int(rng.integers(max(8, w // 8), w // 2))
            bh = int(rng.integers(max(8, h // 8), h // 2))
            x1 = int(rng.integers(0, w - bw))
            y1 = int(rng.integers(0, h - bh))
            label = int(rng.integers(0, num_classes))
            color = _PALETTE[label]
            canvas[y1 : y1 + bh, x1 : x1 + bw] = color
            annotations.append(
                {
                    "id": ann_id,
                    "image_id": image_id,
                    "category_id": label + 1,
                    "bbox": [float(x1), float(y1), float(bw), float(bh)],
                    "area": float(bw * bh),
                    "iscrowd": 0,
                }
            )
            ann_id += 1
        file_name = f"{image_id:06d}.jpg"
        Image.fromarray(canvas).save(os.path.join(img_dir, file_name), quality=92)
        images.append(
            {"id": image_id, "file_name": file_name, "width": w, "height": h}
        )

    blob = {
        "images": images,
        "annotations": annotations,
        "categories": [
            {"id": k + 1, "name": f"class{k}"} for k in range(num_classes)
        ],
    }
    ann_path = os.path.join(root, f"instances_{split}.json")
    # Atomic: concurrent pod workers regenerate the same dataset path, and
    # a reader must never see a half-written annotations file.
    from batchai_retinanet_horovod_coco_tpu.utils.atomicio import (
        atomic_write_text,
    )

    atomic_write_text(ann_path, json.dumps(blob))
    return ann_path
