"""Anchor generation for FPN levels P3..P7.

Capability parity with keras-retinanet's anchor machinery (SURVEY.md M5:
``utils/anchors.py`` — sizes 32..512, strides 8..128, 3 ratios x 3 scales = 9
anchors per location), re-designed for TPU/XLA:

- Anchors are a *static* function of the (bucketed) padded image shape, so we
  compute them once per shape bucket in numpy on host and close over them as
  compile-time constants of the jit'd train/eval step.  XLA constant-folds
  them into the program; nothing is recomputed per step (unlike the reference,
  which regenerates anchors per image inside the data-loader hot loop,
  SURVEY.md call stack 3.3).
- All shapes are fixed: for a given image bucket the anchor count A is a
  Python int, which keeps every downstream op (IoU, matching, NMS) statically
  shaped for the MXU.

Boxes are ``(x1, y1, x2, y2)`` in image pixels throughout the codebase.
"""

from __future__ import annotations

import dataclasses
import math
from functools import lru_cache

import numpy as np


@dataclasses.dataclass(frozen=True)
class AnchorConfig:
    """Anchor pyramid hyperparameters (RetinaNet defaults, Lin et al. 2017)."""

    # One entry per pyramid level P3..P7.
    levels: tuple[int, ...] = (3, 4, 5, 6, 7)
    strides: tuple[int, ...] = (8, 16, 32, 64, 128)
    sizes: tuple[int, ...] = (32, 64, 128, 256, 512)
    ratios: tuple[float, ...] = (0.5, 1.0, 2.0)
    scales: tuple[float, ...] = (2 ** 0.0, 2 ** (1.0 / 3.0), 2 ** (2.0 / 3.0))

    @property
    def num_anchors_per_location(self) -> int:
        return len(self.ratios) * len(self.scales)

    def feature_shape(self, image_hw: tuple[int, int], level: int) -> tuple[int, int]:
        """Feature-map shape at ``level`` for a padded image of ``image_hw``.

        Matches the backbones' conv stride arithmetic — symmetric k//2
        padding (torch geometry, models/resnet.py) — which, like SAME,
        yields ceil(dim / stride) for every input parity.
        """
        stride = self.strides[self.levels.index(level)]
        return (
            int(math.ceil(image_hw[0] / stride)),
            int(math.ceil(image_hw[1] / stride)),
        )

    def num_anchors(self, image_hw: tuple[int, int]) -> int:
        total = 0
        for level in self.levels:
            fh, fw = self.feature_shape(image_hw, level)
            total += fh * fw * self.num_anchors_per_location
        return total


def generate_base_anchors(
    size: float,
    ratios: tuple[float, ...],
    scales: tuple[float, ...],
) -> np.ndarray:
    """(len(ratios)*len(scales), 4) anchors centered at the origin.

    For each (ratio, scale): area = (size*scale)^2, h/w = ratio.  Ordering is
    ratio-major to keep a deterministic layout: index = r * len(scales) + s.
    """
    anchors = []
    for ratio in ratios:
        for scale in scales:
            area = (size * scale) ** 2
            w = math.sqrt(area / ratio)
            h = w * ratio
            anchors.append([-w / 2.0, -h / 2.0, w / 2.0, h / 2.0])
    return np.asarray(anchors, dtype=np.float32)


def _anchors_for_level(
    feat_hw: tuple[int, int],
    stride: int,
    base_anchors: np.ndarray,
) -> np.ndarray:
    """Shift base anchors over every feature-map location → (H*W*K, 4)."""
    fh, fw = feat_hw
    shift_x = (np.arange(fw, dtype=np.float32) + 0.5) * stride
    shift_y = (np.arange(fh, dtype=np.float32) + 0.5) * stride
    sx, sy = np.meshgrid(shift_x, shift_y)  # (fh, fw)
    shifts = np.stack([sx, sy, sx, sy], axis=-1).reshape(-1, 1, 4)  # (H*W,1,4)
    out = shifts + base_anchors[None, :, :]  # (H*W, K, 4)
    return out.reshape(-1, 4).astype(np.float32)


@lru_cache(maxsize=64)
def _anchors_cached(image_hw: tuple[int, int], config: AnchorConfig) -> np.ndarray:
    per_level = []
    for i, level in enumerate(config.levels):
        base = generate_base_anchors(config.sizes[i], config.ratios, config.scales)
        feat_hw = config.feature_shape(image_hw, level)
        per_level.append(_anchors_for_level(feat_hw, config.strides[i], base))
    out = np.concatenate(per_level, axis=0)
    out.setflags(write=False)  # shared cached array: in-place edits would
    return out  # silently corrupt every later caller


def anchors_for_image_shape(
    image_hw: tuple[int, int],
    config: AnchorConfig | None = None,
) -> np.ndarray:
    """All anchors for a padded image shape, concatenated P3→P7: (A, 4).

    Host-side numpy; cached per shape bucket.  The result is closed over by the
    jit'd step as a constant (see ``train/step.py``), making anchor generation
    free at runtime.
    """
    config = config or AnchorConfig()
    return _anchors_cached((int(image_hw[0]), int(image_hw[1])), config)
