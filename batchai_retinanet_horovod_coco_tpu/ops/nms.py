"""Fixed-shape batched NMS for TPU.

Replaces keras-retinanet's ``FilterDetections`` layer (SURVEY.md M6), which
relies on TF's dynamic-shape ``non_max_suppression`` on CPU/GPU.  TPU/XLA
requires static shapes, so the pipeline here is (BASELINE.json:11,
"on-device batched NMS"):

  1. score threshold → invalid entries get score -inf (shape preserved);
  2. top-K pre-selection (``lax.top_k``) to a fixed ``pre_nms_size``;
  3. greedy suppression as a K-step ``fori_loop`` over a precomputed (K, K)
     IoU matrix — O(K^2) memory with K ≤ ~1000, a few MB, fused by XLA;
  4. fixed ``max_detections`` output with a validity mask.

Multi-class NMS uses the class-offset trick: boxes are translated by
``class_id * offset`` so cross-class pairs can never overlap, letting one
single-class pass handle all classes at once (same result as per-class NMS).

Everything vmaps over a leading batch axis.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from batchai_retinanet_horovod_coco_tpu.ops.iou import pairwise_iou

_NEG_INF = -1e9


class Detections(NamedTuple):
    boxes: jnp.ndarray  # (max_detections, 4)
    scores: jnp.ndarray  # (max_detections,)
    labels: jnp.ndarray  # (max_detections,) int32
    valid: jnp.ndarray  # (max_detections,) bool


def single_class_nms(
    boxes: jnp.ndarray,
    scores: jnp.ndarray,
    iou_threshold: float = 0.5,
    max_output: int = 100,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Greedy NMS over (N, 4) boxes / (N,) scores.

    Returns ``(indices, valid)`` of shape (max_output,): indices into the input
    ordered by descending score, with ``valid`` False for suppressed/padded
    slots.  Entries with score ≤ _NEG_INF/2 are treated as padding.
    """
    n = boxes.shape[0]
    order_scores, order = lax.top_k(scores, n)  # full sort by score
    sorted_boxes = boxes[order]

    iou = pairwise_iou(sorted_boxes, sorted_boxes)  # (N, N)

    def body(i, keep):
        # Anchor i survives iff not suppressed by an earlier kept box.
        # Suppress all later boxes overlapping a *kept* box i.
        suppress = (iou[i] > iou_threshold) & keep[i]
        suppress = suppress.at[i].set(False)
        # Only suppress boxes ranked after i (greedy order).
        later = jnp.arange(n) > i
        return keep & ~(suppress & later)

    keep = jnp.ones(n, dtype=bool)
    keep &= order_scores > _NEG_INF / 2  # drop padding
    keep = lax.fori_loop(0, n, body, keep)

    # Compact kept indices to the front, preserving score order.  If fewer
    # candidates than max_output exist, pad with invalid slots.
    kept_scores = jnp.where(keep, order_scores, _NEG_INF)
    k = min(max_output, n)
    _, sel = lax.top_k(kept_scores, k)
    valid = kept_scores[sel] > _NEG_INF / 2
    if k < max_output:
        pad = max_output - k
        sel = jnp.concatenate([sel, jnp.zeros(pad, dtype=sel.dtype)])
        valid = jnp.concatenate([valid, jnp.zeros(pad, dtype=bool)])
    return order[sel], valid


def multiclass_nms(
    boxes: jnp.ndarray,
    cls_scores: jnp.ndarray,
    score_threshold: float = 0.05,
    iou_threshold: float = 0.5,
    pre_nms_size: int = 1000,
    max_detections: int = 300,
    class_offset: float = 1e4,
) -> Detections:
    """All-class NMS over (A, 4) boxes and (A, K) per-class scores.

    Mirrors the reference FilterDetections semantics (score 0.05 → per-class
    NMS 0.5 → top-300, SURVEY.md M6) with fixed shapes.  Each (anchor, class)
    pair is one candidate, as in keras-retinanet's non-class-specific path.
    """
    num_anchors, num_classes = cls_scores.shape
    flat_scores = cls_scores.reshape(-1)  # (A*K,) anchor-major
    flat_scores = jnp.where(flat_scores > score_threshold, flat_scores, _NEG_INF)

    k = min(pre_nms_size, flat_scores.shape[0])
    top_scores, top_idx = lax.top_k(flat_scores, k)
    anchor_idx = top_idx // num_classes
    class_idx = (top_idx % num_classes).astype(jnp.int32)

    cand_boxes = boxes[anchor_idx]  # (k, 4)
    offset_boxes = cand_boxes + (class_idx.astype(cand_boxes.dtype) * class_offset)[
        :, None
    ]

    sel, valid = single_class_nms(
        offset_boxes, top_scores, iou_threshold=iou_threshold, max_output=max_detections
    )
    return Detections(
        boxes=jnp.where(valid[:, None], cand_boxes[sel], 0.0),
        scores=jnp.where(valid, top_scores[sel], _NEG_INF),
        labels=jnp.where(valid, class_idx[sel], -1),
        valid=valid,
    )


def batched_multiclass_nms(
    boxes: jnp.ndarray,
    cls_scores: jnp.ndarray,
    **kwargs,
) -> Detections:
    """vmap of :func:`multiclass_nms` over a leading batch axis.

    Config kwargs are closed over (static), not mapped — passing e.g.
    ``score_threshold=0.1`` works, unlike a bare ``jax.vmap`` with scalar
    kwargs.
    """
    return jax.vmap(lambda b, s: multiclass_nms(b, s, **kwargs))(boxes, cls_scores)
