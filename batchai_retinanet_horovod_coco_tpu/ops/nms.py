"""Fixed-shape batched NMS for TPU.

Replaces keras-retinanet's ``FilterDetections`` layer (SURVEY.md M6), which
relies on TF's dynamic-shape ``non_max_suppression`` on CPU/GPU.  TPU/XLA
requires static shapes, so the pipeline here is (BASELINE.json:11,
"on-device batched NMS"):

  1. score threshold → invalid entries get score -inf (shape preserved);
  2. top-K pre-selection (``lax.top_k``) to a fixed ``pre_nms_size``;
  3. EXACT greedy suppression by fixed-point iteration over a precomputed
     (K, K) IoU matrix — a handful of vectorized passes instead of a K-step
     sequential loop (see single_class_nms);
  4. fixed ``max_detections`` output with a validity mask.

Multi-class NMS runs all classes in one pass by masking the suppressor
matrix to same-class pairs — exactly per-class NMS, with none of the
classic class-offset trick's f32 precision loss (offsetting by
``class_id * 1e4`` puts class-79 coordinates near 7.9e5, where f32 ulp is
~0.06 px and borderline IoU-vs-threshold decisions can flip).

Everything vmaps over a leading batch axis.

Since ISSUE 6 the pipeline's three stages are exposed as named functions —
:func:`select_candidates` (threshold + two-stage top-K),
:func:`greedy_keep` (the exact fixed-point suppression over sorted
candidates) and :func:`compact_keep`/:func:`build_detections` (fixed-width
output) — because the fused Pallas suppression kernel
(ops/pallas/nms.py) shares stages 1 and 3 verbatim and replaces only
stage 2.  Sharing the code, not cloning it, is what makes the two
backends' bit-identity (tests/unit/test_pallas_nms.py) structural rather
than coincidental.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from batchai_retinanet_horovod_coco_tpu.ops.iou import pairwise_iou

_NEG_INF = -1e9


class Detections(NamedTuple):
    boxes: jnp.ndarray  # (max_detections, 4)
    scores: jnp.ndarray  # (max_detections,)
    labels: jnp.ndarray  # (max_detections,) int32
    valid: jnp.ndarray  # (max_detections,) bool


def greedy_keep(
    sorted_boxes: jnp.ndarray,
    sorted_scores: jnp.ndarray,
    iou_threshold: float,
    sorted_class_ids: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Exact greedy-NMS keep mask over boxes ALREADY in descending-score
    order: keep_i ⇔ valid_i ∧ ¬∃ kept j < i with IoU > t (same class).

    EXACT greedy NMS by fixed-point iteration instead of an N-step
    sequential loop: iterating that map from all-valid stabilizes
    front-to-back in score order (position i becomes final once all j < i
    are final), so it converges to the unique greedy solution in
    "suppression chain depth" iterations — typically < 10 — and each
    iteration is one vectorized (N, N) masked any-reduce.  The naive
    N-step fori_loop was pure sequential latency on TPU: ~425 ms of a
    475 ms eval batch at N=1000, B=8; this form measures in single-digit
    ms.  Entries with score ≤ _NEG_INF/2 are padding (never kept, never
    suppressing).

    This is the stage the Pallas suppression kernel (ops/pallas/nms.py)
    replaces; it doubles as that kernel's pure-jnp fallback and parity
    oracle.
    """
    n = sorted_boxes.shape[0]
    iou = pairwise_iou(sorted_boxes, sorted_boxes)  # (N, N)
    if sorted_class_ids is not None:
        iou = jnp.where(
            sorted_class_ids[:, None] == sorted_class_ids[None, :], iou, 0.0
        )
    valid0 = sorted_scores > _NEG_INF / 2  # drop padding
    suppressor = (iou > iou_threshold) & (
        jnp.arange(n)[:, None] < jnp.arange(n)[None, :]
    )  # [j, i]: higher-scored j would suppress i if j is kept

    def cond(carry):
        keep, prev, it = carry
        return jnp.any(keep != prev) & (it < n)

    def body(carry):
        keep, _, it = carry
        suppressed = jnp.any(suppressor & keep[:, None], axis=0)
        return valid0 & ~suppressed, keep, it + 1

    keep, _, _ = lax.while_loop(
        cond, body, (valid0, jnp.zeros_like(valid0), jnp.int32(0))
    )
    return keep


def compact_keep(
    sorted_scores: jnp.ndarray, keep: jnp.ndarray, max_output: int
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Compact kept slots to the front, preserving score order.

    Returns ``(sel, valid)`` of shape (max_output,): indices into the
    sorted candidate order with ``valid`` False for suppressed/padded
    slots.  If fewer candidates than ``max_output`` exist, pads with
    invalid slots.
    """
    n = sorted_scores.shape[0]
    kept_scores = jnp.where(keep, sorted_scores, _NEG_INF)
    k = min(max_output, n)
    _, sel = lax.top_k(kept_scores, k)
    valid = kept_scores[sel] > _NEG_INF / 2
    if k < max_output:
        pad = max_output - k
        sel = jnp.concatenate([sel, jnp.zeros(pad, dtype=sel.dtype)])
        valid = jnp.concatenate([valid, jnp.zeros(pad, dtype=bool)])
    return sel, valid


def single_class_nms(
    boxes: jnp.ndarray,
    scores: jnp.ndarray,
    iou_threshold: float = 0.5,
    max_output: int = 100,
    class_ids: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Greedy NMS over (N, 4) boxes / (N,) scores.

    Returns ``(indices, valid)`` of shape (max_output,): indices into the input
    ordered by descending score, with ``valid`` False for suppressed/padded
    slots.  Entries with score ≤ _NEG_INF/2 are treated as padding.

    With ``class_ids`` (N,), suppression applies only between same-class
    pairs — one pass computes exact per-class NMS over all classes, since
    the IoU matrix is built here anyway and cross-class pairs just drop out
    of the suppressor mask (no coordinate-offset precision hazard).
    """
    n = boxes.shape[0]
    order_scores, order = lax.top_k(scores, n)  # full sort by score
    sorted_boxes = boxes[order]
    sorted_cls = class_ids[order] if class_ids is not None else None

    keep = greedy_keep(sorted_boxes, order_scores, iou_threshold, sorted_cls)
    sel, valid = compact_keep(order_scores, keep, max_output)
    return order[sel], valid


def select_candidates(
    boxes: jnp.ndarray,
    cls_scores: jnp.ndarray,
    score_threshold: float,
    pre_nms_size: int,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Score threshold + two-stage top-K pre-selection (one image).

    Two-stage candidate selection: top anchors by their best class score,
    then top (anchor, class) pairs within those rows.  A direct
    lax.top_k over the (A*K,) flat scores lowers to a full variadic sort
    on TPU — measured 394 ms of a 470 ms eval batch at the flagship
    bucket (B=8, A*K=16.1M); this form measures ~12 ms for the same
    batch.  EXACT up to score ties: with ka = k, every pair of a dropped
    anchor scores below that anchor's best, which scores below all ka
    selected anchors' bests — k of which are already candidate pairs —
    so the selected score multiset equals the global top-k's.

    Returns ``(cand_boxes (k, 4), cand_scores (k,) DESCENDING, class_idx
    (k,) int32)``; sub-threshold slots carry score ``_NEG_INF``.  Shared
    by the XLA and Pallas NMS paths.
    """
    num_anchors, num_classes = cls_scores.shape
    masked = jnp.where(cls_scores > score_threshold, cls_scores, _NEG_INF)
    ka = min(pre_nms_size, num_anchors)
    _, top_anchor = lax.top_k(jnp.max(masked, axis=-1), ka)  # (ka,)
    rows = masked[top_anchor]  # (ka, K) — small gather
    k = min(pre_nms_size, ka * num_classes)
    top_scores, flat_i = lax.top_k(rows.reshape(-1), k)
    anchor_idx = top_anchor[flat_i // num_classes]
    class_idx = (flat_i % num_classes).astype(jnp.int32)
    return boxes[anchor_idx], top_scores, class_idx


def build_detections(
    cand_boxes: jnp.ndarray,
    cand_scores: jnp.ndarray,
    class_idx: jnp.ndarray,
    sel: jnp.ndarray,
    valid: jnp.ndarray,
) -> Detections:
    """Fixed-width Detections from candidates + a compacted selection."""
    return Detections(
        boxes=jnp.where(valid[:, None], cand_boxes[sel], 0.0),
        scores=jnp.where(valid, cand_scores[sel], _NEG_INF),
        labels=jnp.where(valid, class_idx[sel], -1),
        valid=valid,
    )


def multiclass_nms(
    boxes: jnp.ndarray,
    cls_scores: jnp.ndarray,
    score_threshold: float = 0.05,
    iou_threshold: float = 0.5,
    pre_nms_size: int = 1000,
    max_detections: int = 300,
) -> Detections:
    """All-class NMS over (A, 4) boxes and (A, K) per-class scores.

    Mirrors the reference FilterDetections semantics (score 0.05 → per-class
    NMS 0.5 → top-300, SURVEY.md M6) with fixed shapes.  Each (anchor, class)
    pair is one candidate, as in keras-retinanet's non-class-specific path;
    per-class isolation comes from the class-masked suppressor in
    :func:`single_class_nms`, which is exact at any coordinate scale.
    """
    cand_boxes, top_scores, class_idx = select_candidates(
        boxes, cls_scores, score_threshold, pre_nms_size
    )
    sel, valid = single_class_nms(
        cand_boxes,
        top_scores,
        iou_threshold=iou_threshold,
        max_output=max_detections,
        class_ids=class_idx,
    )
    return build_detections(cand_boxes, top_scores, class_idx, sel, valid)


def batched_multiclass_nms(
    boxes: jnp.ndarray,
    cls_scores: jnp.ndarray,
    **kwargs,
) -> Detections:
    """vmap of :func:`multiclass_nms` over a leading batch axis.

    Config kwargs are closed over (static), not mapped — passing e.g.
    ``score_threshold=0.1`` works, unlike a bare ``jax.vmap`` with scalar
    kwargs.
    """
    return jax.vmap(lambda b, s: multiclass_nms(b, s, **kwargs))(boxes, cls_scores)
