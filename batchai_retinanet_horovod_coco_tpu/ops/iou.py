"""Pairwise IoU as a device-side XLA op.

Replaces the reference's Cython ``compute_overlap(boxes, query)`` host kernel
(SURVEY.md M7, ``utils/compute_overlap.pyx``) — the hot inner op of target
assignment that the reference runs per-image on the data-loader CPU thread.
Here it is a broadcasted jnp expression: XLA fuses the whole (A, G) IoU matrix
computation with the downstream argmax of target assignment into a handful of
kernels, and it vmaps cleanly over the batch dimension.

For the training-time shapes (A ≈ 1e5 anchors x G ≤ 100 padded gt boxes,
f32 → ~40 MB per image before fusion) this is elementwise/VPU work that XLA
handles well; a Pallas kernel is not warranted unless profiling shows the
materialized (A, G) intermediate becoming HBM-bound (SURVEY.md §2.5).
"""

from __future__ import annotations

import jax.numpy as jnp


def box_area(boxes: jnp.ndarray) -> jnp.ndarray:
    """Area of (..., 4) corner boxes; degenerate boxes have area 0."""
    w = jnp.maximum(boxes[..., 2] - boxes[..., 0], 0.0)
    h = jnp.maximum(boxes[..., 3] - boxes[..., 1], 0.0)
    return w * h


def pairwise_iou(boxes_a: jnp.ndarray, boxes_b: jnp.ndarray) -> jnp.ndarray:
    """IoU matrix between (N, 4) and (M, 4) corner boxes → (N, M) in [0, 1].

    Degenerate boxes (zero/negative extent, e.g. padding) yield IoU 0 against
    everything, so callers may rely on padded gt rows never matching.
    """
    lt = jnp.maximum(boxes_a[:, None, :2], boxes_b[None, :, :2])  # (N, M, 2)
    rb = jnp.minimum(boxes_a[:, None, 2:], boxes_b[None, :, 2:])  # (N, M, 2)
    wh = jnp.maximum(rb - lt, 0.0)
    intersection = wh[..., 0] * wh[..., 1]
    union = box_area(boxes_a)[:, None] + box_area(boxes_b)[None, :] - intersection
    return jnp.where(union > 0.0, intersection / jnp.maximum(union, 1e-12), 0.0)
