"""Pure, jit-able detection ops: anchors, IoU, box codec, target matching, NMS.

These replace the reference stack's host-side anchor machinery
(keras-retinanet ``utils/anchors.py``, SURVEY.md M5) and its Cython IoU kernel
(``utils/compute_overlap.pyx``, SURVEY.md M7) with device-side XLA ops, per the
north-star requirement that anchor generation and IoU-based target assignment
run as jit'd device-side ops (BASELINE.json:5).
"""

from batchai_retinanet_horovod_coco_tpu.ops.anchors import (
    AnchorConfig,
    anchors_for_image_shape,
    generate_base_anchors,
)
from batchai_retinanet_horovod_coco_tpu.ops.boxes import (
    BoxCodecConfig,
    clip_boxes,
    decode_boxes,
    encode_boxes,
)
from batchai_retinanet_horovod_coco_tpu.ops.iou import pairwise_iou
from batchai_retinanet_horovod_coco_tpu.ops.matching import (
    MatchingConfig,
    anchor_targets,
    assign_anchors,
)
from batchai_retinanet_horovod_coco_tpu.ops.nms import (
    multiclass_nms,
    single_class_nms,
)

__all__ = [
    "AnchorConfig",
    "BoxCodecConfig",
    "MatchingConfig",
    "anchor_targets",
    "anchors_for_image_shape",
    "assign_anchors",
    "clip_boxes",
    "decode_boxes",
    "encode_boxes",
    "generate_base_anchors",
    "multiclass_nms",
    "pairwise_iou",
    "single_class_nms",
]
