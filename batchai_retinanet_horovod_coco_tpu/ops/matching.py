"""IoU-based anchor→gt target assignment as a jit'd device op.

Capability parity with keras-retinanet's ``anchor_targets_bbox`` /
``compute_gt_annotations`` (SURVEY.md M5): per-anchor argmax-IoU assignment
with IoU ≥ 0.5 positive, < 0.4 negative, in-between ignored — but executed on
device, vmapped over the batch, instead of per-image on the host loader thread
(SURVEY.md call stack 3.3).

Design notes (TPU-first):
- GT boxes arrive padded to a fixed ``max_gt`` with a validity mask, keeping
  every shape static.  Padded rows are degenerate boxes → IoU 0 → can never
  become positives; we additionally mask them explicitly for robustness.
- In addition to the per-anchor rule we force-assign, for every valid gt, the
  anchor with the highest IoU (the RetinaNet paper's low-quality-match rescue;
  without it small objects can end up with zero positive anchors).
- Outputs are dense fixed-shape tensors consumed directly by the losses.
  The train step uses the compact form (:func:`anchor_targets_compact`):
  integer matched labels, box-delta targets, and a per-anchor state in
  {-1 ignore, 0 negative, 1 positive}; the focal loss reconstructs the
  one-hot implicitly.  :func:`anchor_targets` materializes the one-hot
  (A, K) form for tests/tools (the keras-retinanet surface).
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp

from batchai_retinanet_horovod_coco_tpu.ops.boxes import (
    BoxCodecConfig,
    encode_boxes,
    encode_boxes_planar,
)
from batchai_retinanet_horovod_coco_tpu.ops.iou import pairwise_iou

IGNORE = -1
NEGATIVE = 0
POSITIVE = 1


@dataclasses.dataclass(frozen=True)
class MatchingConfig:
    positive_iou: float = 0.5
    negative_iou: float = 0.4
    # Force-match each gt's best anchor even below positive_iou.
    force_match_best: bool = True
    # Batched assignment via the fused Pallas kernel (ops/pallas/matching.py)
    # instead of the vmapped XLA lowering: None = auto (TPU backend only),
    # True/False = force.  See the kernel module docstring for the measured
    # HBM-traffic win.
    fused_pallas: bool | None = None
    # Interpreter-mode pallas (CPU tests of the fused path).
    pallas_interpret: bool = False
    # Anchor-tile width for the fused kernel: None = the schedule-resolved
    # or module default (ops/pallas/matching.TILE_A).  A searched schedule
    # parameter — train/step.py fills it from the per-device registry
    # (tune/schedule.py) when left None.
    pallas_tile_a: int | None = None


class AnchorAssignment(NamedTuple):
    matched_gt: jnp.ndarray  # (A,) int32 index into gt rows (0 if unmatched)
    state: jnp.ndarray  # (A,) int32 in {IGNORE, NEGATIVE, POSITIVE}


class AnchorTargets(NamedTuple):
    cls_targets: jnp.ndarray  # (A, num_classes) one-hot float
    box_targets: jnp.ndarray  # (A, 4) encoded deltas (valid where positive)
    state: jnp.ndarray  # (A,) int32


class CompactTargets(NamedTuple):
    """Targets without the dense (A, K) one-hot — the train-step form.

    The one-hot classification target is recoverable as
    ``(matched_labels[:, None] == arange(K)) & (state == POSITIVE)``; keeping
    it implicit lets the focal loss fuse that comparison into its elementwise
    computation instead of writing a (B, A, K) float tensor to HBM (~0.5 GB
    per step at the flagship bucket — measured 49 ms → see losses.py).
    """

    matched_labels: jnp.ndarray  # (A,) int32 class id of the matched gt
    box_targets: jnp.ndarray  # (A, 4) encoded deltas (valid where positive)
    state: jnp.ndarray  # (A,) int32


def _finalize_states(
    max_iou: jnp.ndarray,
    gt_best_iou: jnp.ndarray,
    gt_best_anchor: jnp.ndarray,
    gt_mask: jnp.ndarray,
    num_anchors: int,
    config: MatchingConfig,
) -> tuple[jnp.ndarray, jnp.ndarray | None]:
    """The matching RULE, shared by the XLA and fused-Pallas paths.

    Thresholds + force-match rescue from the per-anchor/per-gt IoU
    reductions (one image).  Returns ``(state, forced_target)`` where
    ``forced_target`` (G,) routes each force-matched gt to its best anchor
    (out-of-range index ``num_anchors`` = not forced, dropped by scatters);
    None when force-matching is disabled.

    Keeping this in ONE place is what guarantees the two assignment
    backends can never drift apart on the rule itself (the kernels only
    compute reductions; tests/unit/test_pallas_matching.py pins equality).
    """
    any_gt = jnp.any(gt_mask)
    positive = (max_iou >= config.positive_iou) & any_gt
    negative = max_iou < config.negative_iou

    forced_target = None
    if config.force_match_best:
        # For each valid gt with some overlap (> 0), its argmax anchor
        # becomes positive for that gt.  Non-forced gts (padding / no
        # overlap) are routed to out-of-range index A so mode="drop"
        # discards them — they must not clobber real writes at anchor 0
        # (argmax of an all-zero IoU column is 0).
        force = gt_mask & (gt_best_iou > 0.0)
        forced_target = jnp.where(force, gt_best_anchor, num_anchors)
        forced_flag = jnp.zeros(num_anchors, dtype=bool).at[forced_target].set(
            True, mode="drop"
        )
        positive = positive | forced_flag
        negative = negative & ~forced_flag

    state = jnp.full(num_anchors, IGNORE, dtype=jnp.int32)
    state = jnp.where(negative, NEGATIVE, state)
    state = jnp.where(positive, POSITIVE, state)
    return state, forced_target


def assign_anchors(
    anchors: jnp.ndarray,
    gt_boxes: jnp.ndarray,
    gt_mask: jnp.ndarray,
    config: MatchingConfig = MatchingConfig(),
) -> AnchorAssignment:
    """Assign each of A anchors to one of G (padded) gt boxes.

    Args:
      anchors: (A, 4) corner boxes.
      gt_boxes: (G, 4) corner boxes, padded rows arbitrary.
      gt_mask: (G,) bool, True for real gt rows.
    """
    num_anchors = anchors.shape[0]
    iou = pairwise_iou(anchors, gt_boxes)  # (A, G)
    iou = jnp.where(gt_mask[None, :], iou, 0.0)

    matched_gt = jnp.argmax(iou, axis=1).astype(jnp.int32)  # (A,)
    max_iou = jnp.max(iou, axis=1)  # (A,)

    state, forced_target = _finalize_states(
        max_iou,
        jnp.max(iou, axis=0),
        jnp.argmax(iou, axis=0).astype(jnp.int32),
        gt_mask,
        num_anchors,
        config,
    )
    if forced_target is not None:
        forced_flag = jnp.zeros(num_anchors, dtype=bool).at[forced_target].set(
            True, mode="drop"
        )
        forced_idx = (
            jnp.zeros(num_anchors, dtype=jnp.int32)
            .at[forced_target]
            .set(jnp.arange(gt_boxes.shape[0], dtype=jnp.int32), mode="drop")
        )
        matched_gt = jnp.where(forced_flag, forced_idx, matched_gt)
    return AnchorAssignment(matched_gt=matched_gt, state=state)


def anchor_targets_compact(
    anchors: jnp.ndarray,
    gt_boxes: jnp.ndarray,
    gt_labels: jnp.ndarray,
    gt_mask: jnp.ndarray,
    matching: MatchingConfig = MatchingConfig(),
    codec: BoxCodecConfig = BoxCodecConfig(),
) -> CompactTargets:
    """Per-anchor targets for one image, classification kept as int labels.

    vmap over a leading batch axis for batched use (anchors held constant):
    ``jax.vmap(anchor_targets_compact, in_axes=(None, 0, 0, 0))``.
    """
    assignment = assign_anchors(anchors, gt_boxes, gt_mask, matching)
    # Matched gt rows via one-hot matmul rather than a gather: a TPU gather of
    # ~200k rows from a tiny table serializes (profiled at ~20 ms/step at the
    # flagship bucket, the single hottest op) while the (A, G) @ (G, 5) dot is
    # MXU work measured at ~2 ms.  HIGHEST precision keeps it bit-exact in
    # f32 (each one-hot row selects exactly one value; default TPU matmul
    # precision would round coords through bf16).
    num_gt = gt_boxes.shape[0]
    onehot = (
        assignment.matched_gt[:, None] == jnp.arange(num_gt, dtype=jnp.int32)
    ).astype(jnp.float32)  # (A, G)
    packed = jnp.concatenate(
        [gt_boxes.astype(jnp.float32), gt_labels.astype(jnp.float32)[:, None]],
        axis=1,
    )  # (G, 5): x1 y1 x2 y2 label
    matched = jnp.dot(onehot, packed, precision=jax.lax.Precision.HIGHEST)
    matched_boxes = matched[:, :4]  # (A, 4)
    matched_labels = matched[:, 4].astype(jnp.int32)  # (A,)

    positive = assignment.state == POSITIVE
    box_targets = encode_boxes(anchors, matched_boxes, codec)
    box_targets = jnp.where(positive[:, None], box_targets, 0.0)
    return CompactTargets(
        matched_labels=matched_labels,
        box_targets=box_targets,
        state=assignment.state,
    )


def anchor_targets_compact_batched(
    anchors: jnp.ndarray,
    gt_boxes: jnp.ndarray,
    gt_labels: jnp.ndarray,
    gt_mask: jnp.ndarray,
    matching: MatchingConfig = MatchingConfig(),
    codec: BoxCodecConfig = BoxCodecConfig(),
    planar_box_targets: bool = False,
) -> CompactTargets:
    """Batched :func:`anchor_targets_compact` — the train-step entrypoint.

    Dispatches between the vmapped XLA path and the fused Pallas kernel
    (``MatchingConfig.fused_pallas``); both produce identical targets
    (tests/unit/test_pallas_matching.py).  Inputs carry a leading batch dim
    except ``anchors`` (shared).

    ``planar_box_targets``: return ``box_targets`` coordinate-planar as
    (B, 4, A) instead of (B, A, 4).  On TPU a 4-minor f32 tensor tiles at
    ~3% lane occupancy (206 MB of T(8,128) tiles at the flagship bucket),
    and every op touching it — the kernel-output moveaxis, the encode, the
    positive mask, the per-level loss retile — pays that tax; the planar
    form is the same values in a dense layout (identical per-element
    arithmetic, see ops.boxes.encode_boxes_planar).  The train step's NHWC
    loss path consumes this form.
    """
    fused = matching.fused_pallas
    if fused is None:
        fused = jax.default_backend() == "tpu"
    if not fused:
        targets = jax.vmap(
            anchor_targets_compact, in_axes=(None, 0, 0, 0, None, None)
        )(anchors, gt_boxes, gt_labels, gt_mask, matching, codec)
        if planar_box_targets:
            targets = targets._replace(
                box_targets=jnp.moveaxis(targets.box_targets, -1, -2)
            )
        return targets

    from batchai_retinanet_horovod_coco_tpu.ops.pallas.matching import (
        assign_fused,
    )

    matched_boxes, matched_labels, max_iou, gt_best_iou, gt_best_anchor = (
        assign_fused(
            anchors, gt_boxes, gt_labels, gt_mask,
            interpret=matching.pallas_interpret,
            planar=planar_box_targets,
            tile_a=matching.pallas_tile_a,
        )
    )
    num_anchors = anchors.shape[0]

    def finish_one(miou, best_iou, best_anchor, boxes, labels, mask, mb, ml):
        state, forced_target = _finalize_states(
            miou, best_iou, best_anchor, mask, num_anchors, matching
        )
        if forced_target is not None:
            # The kernel's matched rows reflect the pre-force argmax; patch
            # the ≤G force-matched anchors with their gt's box/label.
            if planar_box_targets:
                # mb is (4, A): scatter the gt coords along lanes.
                mb = mb.at[:, forced_target].set(
                    jnp.moveaxis(boxes.astype(jnp.float32), 0, 1), mode="drop"
                )
            else:
                mb = mb.at[forced_target].set(
                    boxes.astype(jnp.float32), mode="drop"
                )
            ml = ml.at[forced_target].set(
                labels.astype(jnp.int32), mode="drop"
            )
        return state, mb, ml

    state, matched_boxes, matched_labels = jax.vmap(finish_one)(
        max_iou, gt_best_iou, gt_best_anchor, gt_boxes, gt_labels, gt_mask,
        matched_boxes, matched_labels,
    )

    positive = state == POSITIVE
    if planar_box_targets:
        box_targets = encode_boxes_planar(
            jnp.moveaxis(anchors, 0, 1)[None], matched_boxes, codec
        )
        box_targets = jnp.where(positive[..., None, :], box_targets, 0.0)
    else:
        box_targets = encode_boxes(anchors[None], matched_boxes, codec)
        box_targets = jnp.where(positive[..., None], box_targets, 0.0)
    return CompactTargets(
        matched_labels=matched_labels,
        box_targets=box_targets,
        state=state,
    )


def anchor_targets(
    anchors: jnp.ndarray,
    gt_boxes: jnp.ndarray,
    gt_labels: jnp.ndarray,
    gt_mask: jnp.ndarray,
    num_classes: int,
    matching: MatchingConfig = MatchingConfig(),
    codec: BoxCodecConfig = BoxCodecConfig(),
) -> AnchorTargets:
    """Dense per-anchor classification + regression targets for one image.

    The keras-retinanet ``anchor_targets_bbox`` surface (one-hot cls targets).
    The train step uses :func:`anchor_targets_compact` instead — materializing
    (A, K) here is fine for tests/tools but wasteful inside the hot step.
    The one-hot is built with a broadcast compare, not a scatter: TPU scatter
    serializes; an (A, K) equality against an iota vectorizes on the VPU.
    """
    compact = anchor_targets_compact(
        anchors, gt_boxes, gt_labels, gt_mask, matching, codec
    )
    positive = compact.state == POSITIVE
    cls_targets = jnp.where(
        positive[:, None]
        & (
            compact.matched_labels[:, None]
            == jnp.arange(num_classes, dtype=jnp.int32)[None, :]
        ),
        1.0,
        0.0,
    ).astype(jnp.float32)
    return AnchorTargets(
        cls_targets=cls_targets,
        box_targets=compact.box_targets,
        state=compact.state,
    )
