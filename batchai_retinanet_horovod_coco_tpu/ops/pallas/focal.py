"""Fused focal-loss Pallas kernel (forward + custom VJP) — opt-in.

An alternative lowering of ``losses.focal_loss_compact`` on TPU.  It does
one read of the logits per direction:

- forward: one pass computing the per-image masked focal sum directly
  (nothing materialized except a (B, 1) output);
- backward: one pass recomputing p from the logits and emitting
  d(loss_sum_b)/d(logits) scaled by the incoming cotangent — no residuals
  beyond the inputs themselves.

The implicit one-hot target ``(state == POSITIVE) & (label == k)`` is
reconstructed inside the kernel from the integer labels (same contract as
``losses.focal_loss_compact``).  Normalization (per-image /num_pos, batch
mean) stays outside — it is (B,)-shaped math.

Closed-form gradient (p = sigmoid(x), per element):
  t=1:  alpha   * (1-p)^gamma * (gamma * p * log(p) + p - 1)
  t=0:  (1-a)   * p^gamma     * (p - gamma * (1-p) * log(1-p))
with log(p) = -softplus(-x), log(1-p) = -softplus(x) for stability.
Validated against jax.grad of the jnp implementation in
tests/unit/test_pallas_focal.py.

MEASURED (v5e-1, flagship bucket B=8, A=201600, K=80, f32): this kernel is
SLOWER than XLA's lowering of the exp-form jnp path — 7.9 vs 3.6 ms forward,
12.7 vs 4.5 ms fwd+bwd — because K=80 occupies only 80 of 128 VPU lanes in
every (TILE_A, K) block (37% waste) and the (1, TILE_A, 80) HBM->VMEM DMAs
pipeline worse than XLA's chosen layout.  It is therefore OFF by default
(``LossConfig.pallas_focal``); kept, tested, and wired for workloads with
K >= 128 where the lane padding vanishes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import nn
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Anchor-tile sizes, bounded by the ~16MB scoped-vmem budget: each live
# (TILE_A, 80) f32 temp is TILE_A*80*4 bytes, and the backward kernel holds
# more of them at once (grad output + recomputed p/log terms), so it tiles
# smaller.  8192 OOMs backward at K=80 (19.5M scoped); 4096 fits.
FWD_TILE_A = 8192
BWD_TILE_A = 4096


def _masked_target(labels, state, shape_ak):
    """Implicit one-hot: (TILE_A, K) bool target + (TILE_A, 1) row mask.

    All broadcasts happen on 2-D int32 values — Mosaic only supports
    inserting a minor dim on 32-bit types, so the bool compares come after
    the [:, None] expansion, never before.
    """
    kcol = jax.lax.broadcasted_iota(jnp.int32, shape_ak, 1)
    labels2 = labels[:, None]  # (TILE_A, 1) int32
    state2 = state[:, None]
    t = (state2 == 1) & (labels2 == kcol)
    not_ignored = state2 != -1  # (TILE_A, 1)
    return t, not_ignored


def _fwd_kernel(labels_ref, state_ref, logits_ref, out_ref, *, alpha, gamma, num_anchors):
    tile = pl.program_id(1)
    x = logits_ref[0].astype(jnp.float32)  # (TILE_A, K)
    labels = labels_ref[0, 0]  # (TILE_A,)
    state = state_ref[0, 0]

    t, not_ignored = _masked_target(labels, state, x.shape)
    row = jax.lax.broadcasted_iota(jnp.int32, (x.shape[0], 1), 0)
    in_range = (tile * x.shape[0] + row) < num_anchors  # (TILE_A, 1)
    valid = not_ignored & in_range

    # Exponential form (see losses.focal_loss): bce = sp_pos - x*t,
    # (1-p_t)^gamma = exp(-gamma*(sp_neg + x*t)) — one softplus + one exp.
    sp_neg = nn.softplus(-x)
    xt = jnp.where(t, x, 0.0)
    bce = sp_neg + x - xt
    modulator = jnp.exp(-gamma * (sp_neg + xt))
    alpha_t = jnp.where(t, alpha, 1.0 - alpha)
    loss = alpha_t * modulator * bce
    partial = jnp.sum(jnp.where(valid, loss, 0.0))

    @pl.when(tile == 0)
    def _():
        out_ref[0, 0, 0] = 0.0

    out_ref[0, 0, 0] += partial


def _bwd_kernel(
    labels_ref, state_ref, logits_ref, g_ref, dx_ref, *, alpha, gamma, num_anchors
):
    tile = pl.program_id(1)
    x = logits_ref[0].astype(jnp.float32)
    labels = labels_ref[0, 0]  # (TILE_A,)
    state = state_ref[0, 0]
    g = g_ref[0, 0, 0]

    t, not_ignored = _masked_target(labels, state, x.shape)
    row = jax.lax.broadcasted_iota(jnp.int32, (x.shape[0], 1), 0)
    in_range = (tile * x.shape[0] + row) < num_anchors  # (TILE_A, 1)
    valid = not_ignored & in_range

    # Exponential form of the closed-form gradient (docstring): with
    # sp_neg = -log p, sp_pos = -log(1-p), p = exp(-sp_neg):
    #   t=1:  alpha   * exp(-g*sp_pos) * (p - 1 - g*p*sp_neg)
    #   t=0:  (1-a)   * exp(-g*sp_neg) * (p + g*(1-p)*sp_pos)
    # and exp(-g*(sp_neg + x*t)) covers both modulators in one exp.
    sp_neg = nn.softplus(-x)
    sp_pos = x + sp_neg
    xt = jnp.where(t, x, 0.0)
    modulator = jnp.exp(-gamma * (sp_neg + xt))
    p = jnp.exp(-sp_neg)
    inner = jnp.where(
        t, p - 1.0 - gamma * p * sp_neg, p + gamma * (1.0 - p) * sp_pos
    )
    alpha_t = jnp.where(t, alpha, 1.0 - alpha)
    grad = alpha_t * modulator * inner
    grad = jnp.where(valid, grad, 0.0) * g
    dx_ref[0] = grad.astype(dx_ref.dtype)


def _row_spec(tile_a):
    # labels/state ship as (B, 1, A): rank-3 so the BLOCKED last-two dims are
    # (1, TILE_A) — legal Mosaic tiling (1 == full middle dim, TILE_A % 128
    # == 0) — while a rank-2 (B, A) block of (1, TILE_A) is rejected.
    return pl.BlockSpec(
        (1, 1, tile_a), lambda b, t: (b, 0, t), memory_space=pltpu.VMEM
    )


def _call_fwd(
    cls_logits, matched_labels, anchor_state, alpha, gamma, interpret,
    tile_a=None,
):
    tile = FWD_TILE_A if tile_a is None else int(tile_a)
    batch, num_anchors, _ = cls_logits.shape
    grid = (batch, pl.cdiv(num_anchors, tile))
    out = pl.pallas_call(
        functools.partial(
            _fwd_kernel, alpha=alpha, gamma=gamma, num_anchors=num_anchors
        ),
        grid=grid,
        in_specs=[
            _row_spec(tile),
            _row_spec(tile),
            pl.BlockSpec(
                (1, tile, cls_logits.shape[-1]),
                lambda b, t: (b, t, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, 1), lambda b, t: (b, 0, 0), memory_space=pltpu.SMEM
        ),
        out_shape=jax.ShapeDtypeStruct((batch, 1, 1), jnp.float32),
        # allow_input_fusion on the logits: the producer (per-level head
        # outputs transposed+concatenated to (B, A, K)) fuses into the kernel
        # instead of materializing in HBM — the whole point of fusing focal.
        compiler_params=None
        if interpret
        else pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
            allow_input_fusion=[False, False, True],
            vmem_limit_bytes=64 * 1024 * 1024,
        ),
        interpret=interpret,
    )(matched_labels[:, None, :], anchor_state[:, None, :], cls_logits)
    return out[:, 0, 0]


def _call_bwd(
    cls_logits, matched_labels, anchor_state, g, alpha, gamma, interpret,
    tile_a=None,
):
    tile = BWD_TILE_A if tile_a is None else int(tile_a)
    batch, num_anchors, _ = cls_logits.shape
    grid = (batch, pl.cdiv(num_anchors, tile))
    return pl.pallas_call(
        functools.partial(
            _bwd_kernel, alpha=alpha, gamma=gamma, num_anchors=num_anchors
        ),
        grid=grid,
        in_specs=[
            _row_spec(tile),
            _row_spec(tile),
            pl.BlockSpec(
                (1, tile, cls_logits.shape[-1]),
                lambda b, t: (b, t, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, 1), lambda b, t: (b, 0, 0), memory_space=pltpu.SMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, tile, cls_logits.shape[-1]),
            lambda b, t: (b, t, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct(cls_logits.shape, cls_logits.dtype),
        compiler_params=None
        if interpret
        else pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel"),
            allow_input_fusion=[False, False, True, False],
            vmem_limit_bytes=64 * 1024 * 1024,
        ),
        interpret=interpret,
    )(
        matched_labels[:, None, :],
        anchor_state[:, None, :],
        cls_logits,
        g.reshape(batch, 1, 1).astype(jnp.float32),
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def focal_loss_per_image_sums(
    cls_logits: jnp.ndarray,
    matched_labels: jnp.ndarray,
    anchor_state: jnp.ndarray,
    alpha: float = 0.25,
    gamma: float = 2.0,
    interpret: bool = False,
    fwd_tile_a: int | None = None,
    bwd_tile_a: int | None = None,
) -> jnp.ndarray:
    """Per-image focal-loss sums (B,) over non-ignored anchors, fused on TPU.

    Args:
      cls_logits: (B, A, K) raw logits (any float dtype; computed in f32).
      matched_labels: (B, A) int32 matched class ids (read where positive).
      anchor_state: (B, A) int32 in {-1 ignore, 0 negative, 1 positive}.
      interpret: run the kernel in interpreter mode (CPU testing).
      fwd_tile_a / bwd_tile_a: anchor-tile widths (None = the module
        defaults FWD_TILE_A/BWD_TILE_A).  Searched schedule parameters
        (tune/candidates.FOCAL_FWD_TILES/FOCAL_BWD_TILES) — must be
        positive multiples of 128; the backward ceiling is lower because
        it holds more live temps (see the constants' note above).

    Gradients flow to ``cls_logits`` only.
    """
    return _call_fwd(
        cls_logits, matched_labels, anchor_state, alpha, gamma, interpret,
        fwd_tile_a,
    )


def _vjp_fwd(
    cls_logits, matched_labels, anchor_state, alpha, gamma, interpret,
    fwd_tile_a, bwd_tile_a,
):
    out = _call_fwd(
        cls_logits, matched_labels, anchor_state, alpha, gamma, interpret,
        fwd_tile_a,
    )
    return out, (cls_logits, matched_labels, anchor_state)


def _vjp_bwd(
    alpha, gamma, interpret, fwd_tile_a, bwd_tile_a, residuals, g
):
    cls_logits, matched_labels, anchor_state = residuals
    dx = _call_bwd(
        cls_logits, matched_labels, anchor_state, g, alpha, gamma, interpret,
        bwd_tile_a,
    )
    return dx, None, None


focal_loss_per_image_sums.defvjp(_vjp_fwd, _vjp_bwd)
