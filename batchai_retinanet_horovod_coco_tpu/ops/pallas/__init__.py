"""Pallas TPU kernels for hot ops (SURVEY.md §2.5).

Each kernel ships with a pure-jnp fallback and is validated against it in
interpret mode on CPU (tests/unit/test_pallas_focal.py).  Kernels are
opt-in: they are only used where they measure faster than XLA's lowering
on real hardware (see each module's MEASURED note).
"""

from batchai_retinanet_horovod_coco_tpu.ops.pallas.focal import (
    focal_loss_per_image_sums,
)
from batchai_retinanet_horovod_coco_tpu.ops.pallas.matching import (
    assign_fused,
)
from batchai_retinanet_horovod_coco_tpu.ops.pallas.nms import (
    batched_multiclass_nms_pallas,
)

__all__ = [
    "assign_fused",
    "batched_multiclass_nms_pallas",
    "focal_loss_per_image_sums",
]
