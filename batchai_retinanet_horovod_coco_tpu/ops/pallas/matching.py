"""Fused anchor→gt assignment Pallas kernel.

The XLA lowering of ``ops.matching.anchor_targets_compact`` materializes the
pairwise geometry in HBM — profiled at the flagship bucket (B=8, A=201600,
G=100): an f32[8, 201600, 100, 2] corner max/min intermediate (~1.3 GB of
writes+reads), two (A, G) argmax reductions, and the one-hot
(A, G) @ (G, 5) lookup matmul — ~9.5 ms end to end.  This kernel streams
anchor tiles through VMEM and never materializes anything A×G-shaped
off-chip.

Layout is chosen for the VPU: anchors ride the 128-lane minor dim and the
G gt boxes ride sublanes, so the per-anchor max/argmax over gts are FAST
sublane reductions over a (G, TILE_A) tile, and the matched-row lookup is
one f32 MXU dot ``packed^T (8, G) @ onehot (G, TILE_A)`` (HIGHEST precision
— each one-hot column selects exactly one row, so the result is bit-exact
f32).  The per-gt best-anchor reduction (force-match rescue) is the only
cross-lane reduce, done once per tile into a (G, 8) running accumulator.

IoU semantics match ``ops.iou.pairwise_iou`` exactly (degenerate/padded
boxes → IoU 0); tie-breaking matches ``jnp.argmax`` (first maximum).
Thresholding, the ≤G-row force-match scatter, and box encoding stay in jnp
(ops/matching.py) — (A,)-shaped, cheap, shared with the reference path.
Validated against the jnp path in tests/unit/test_pallas_matching.py.

MEASURED (v5e-1, flagship bucket B=8, A=201600, G=100): 5.4 ms vs 11.8 ms
for the XLA lowering in isolation (2.2x); inside the full train step the
wall-clock gain is small (~0.4 ms — XLA overlaps most of the matching with
conv work) but the kernel removes the 1.3 GB A×G HBM intermediate, which
lowers peak-memory pressure at larger batches.  An earlier layout with
anchors on sublanes and G on lanes measured 15.6 ms — every per-anchor
reduction was a cross-lane op; the transpose is what makes this kernel win.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TILE_A = 8192

# Row layout of the transposed per-anchor result (B, 8, A).
ROW_MAX_IOU = 5  # rows 0..3 = matched box, 4 = label, 5 = max IoU
# Column layout of the per-gt accumulator (B, G, 8).
GT_COL_IOU, GT_COL_ANCHOR = 0, 1


def _kernel(anchors_ref, gt_ref, packedT_ref, out_ref, gtbest_ref, *, num_anchors):
    t = pl.program_id(1)
    a = anchors_ref[...].astype(jnp.float32)  # (4, TILE_A)
    gt = gt_ref[0].astype(jnp.float32)  # (G, 6): x1 y1 x2 y2 mask area
    packed_t = packedT_ref[0].astype(jnp.float32)  # (8, G)
    tile_a = a.shape[1]
    num_gt = gt.shape[0]

    x1a, y1a, x2a, y2a = (a[i : i + 1, :] for i in range(4))  # (1, TILE_A)
    x1g, y1g, x2g, y2g = (gt[:, i : i + 1] for i in range(4))  # (G, 1)
    gt_valid = gt[:, 4:5] > 0.0  # (G, 1)
    area_g = gt[:, 5:6]  # (G, 1)

    # IoU — same arithmetic as ops.iou.pairwise_iou.  (G, TILE_A)
    iw = jnp.maximum(jnp.minimum(x2a, x2g) - jnp.maximum(x1a, x1g), 0.0)
    ih = jnp.maximum(jnp.minimum(y2a, y2g) - jnp.maximum(y1a, y1g), 0.0)
    inter = iw * ih
    area_a = jnp.maximum(x2a - x1a, 0.0) * jnp.maximum(y2a - y1a, 0.0)
    union = area_a + area_g - inter
    iou = jnp.where(union > 0.0, inter / jnp.maximum(union, 1e-12), 0.0)
    iou = jnp.where(gt_valid, iou, 0.0)

    lane = jax.lax.broadcasted_iota(jnp.int32, (1, tile_a), 1)
    in_range = (t * tile_a + lane) < num_anchors  # (1, TILE_A)
    # Out-of-range anchors must not win the per-gt argmax below.
    iou = jnp.where(in_range, iou, -1.0)

    # Per-anchor max + first-argmax over gts: sublane reductions.
    max_iou = jnp.max(iou, axis=0, keepdims=True)  # (1, TILE_A)
    grow = jax.lax.broadcasted_iota(jnp.int32, iou.shape, 0)
    first = jnp.min(
        jnp.where(iou == max_iou, grow, num_gt), axis=0, keepdims=True
    )  # (1, TILE_A)
    onehot = (grow == first).astype(jnp.float32)  # (G, TILE_A)
    sel = jax.lax.dot_general(
        packed_t,
        onehot,
        (((1,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
    )  # (8, TILE_A)
    row8 = jax.lax.broadcasted_iota(jnp.int32, sel.shape, 0)
    out_ref[0] = sel + jnp.where(row8 == ROW_MAX_IOU, max_iou, 0.0)

    # Per-gt running best across anchor tiles (first-tie like jnp.argmax:
    # strict > keeps the earlier tile; min-of-lanes breaks ties within one).
    tile_best = jnp.max(iou, axis=1, keepdims=True)  # (G, 1)
    lane_global = (t * tile_a + lane).astype(jnp.int32)
    tile_arg = jnp.min(
        jnp.where(iou == tile_best, lane_global, num_anchors),
        axis=1,
        keepdims=True,
    ).astype(jnp.float32)
    gcol = jax.lax.broadcasted_iota(jnp.int32, (num_gt, 8), 1)
    update = (
        tile_best * (gcol == GT_COL_IOU) + tile_arg * (gcol == GT_COL_ANCHOR)
    )

    @pl.when(t == 0)
    def _():
        gtbest_ref[0] = update

    @pl.when(t > 0)
    def _():
        cur = gtbest_ref[0]  # (G, 8)
        better = cur[:, GT_COL_IOU : GT_COL_IOU + 1] < tile_best  # (G, 1)
        gtbest_ref[0] = jnp.where(better, update, cur)


@functools.partial(
    jax.jit, static_argnames=("interpret", "planar", "tile_a")
)
def assign_fused(
    anchors: jnp.ndarray,
    gt_boxes: jnp.ndarray,
    gt_labels: jnp.ndarray,
    gt_mask: jnp.ndarray,
    interpret: bool = False,
    planar: bool = False,
    tile_a: int | None = None,
):
    """Batched fused assignment.

    Args:
      anchors: (A, 4) f32 corner boxes (shared across the batch).
      gt_boxes: (B, G, 4) padded corner boxes.
      gt_labels: (B, G) int32.
      gt_mask: (B, G) bool.
      planar: return matched boxes coordinate-planar (B, 4, A) — a FREE
        slice of the kernel's transposed output, where the default (B, A, 4)
        form costs a moveaxis copy of a 32x-lane-padded tensor (~206 MB of
        tiles at the flagship bucket; see ops.boxes.encode_boxes_planar).
      tile_a: anchor-tile width (None = module default TILE_A).  A searched
        schedule parameter (tune/candidates.MATCHING_TILES); must be a
        positive multiple of 128.

    Returns:
      matched_boxes (B, A, 4) f32 — or (B, 4, A) when ``planar`` —
      matched_labels (B, A) int32, max_iou (B, A) f32, gt_best_iou (B, G)
      f32, gt_best_anchor (B, G) int32.
    """
    tile = TILE_A if tile_a is None else int(tile_a)
    batch, num_gt, _ = gt_boxes.shape
    num_anchors = anchors.shape[0]
    boxes = gt_boxes.astype(jnp.float32)
    w = jnp.maximum(boxes[..., 2] - boxes[..., 0], 0.0)
    h = jnp.maximum(boxes[..., 3] - boxes[..., 1], 0.0)
    gt = jnp.concatenate(
        [
            boxes,
            gt_mask[..., None].astype(jnp.float32),
            (w * h)[..., None],
        ],
        axis=-1,
    )  # (B, G, 6)
    packed_t = jnp.stack(
        [
            boxes[..., 0], boxes[..., 1], boxes[..., 2], boxes[..., 3],
            gt_labels.astype(jnp.float32),
            jnp.zeros((batch, num_gt), jnp.float32),
            jnp.zeros((batch, num_gt), jnp.float32),
            jnp.zeros((batch, num_gt), jnp.float32),
        ],
        axis=1,
    )  # (B, 8, G)

    grid = (batch, pl.cdiv(num_anchors, tile))
    out, gtbest = pl.pallas_call(
        functools.partial(_kernel, num_anchors=num_anchors),
        grid=grid,
        in_specs=[
            pl.BlockSpec((4, tile), lambda b, t: (0, t),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, num_gt, 6), lambda b, t: (b, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 8, num_gt), lambda b, t: (b, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, 8, tile), lambda b, t: (b, 0, t),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, num_gt, 8), lambda b, t: (b, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((batch, 8, num_anchors), jnp.float32),
            jax.ShapeDtypeStruct((batch, num_gt, 8), jnp.float32),
        ],
        compiler_params=None
        if interpret
        else pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
            vmem_limit_bytes=64 * 1024 * 1024,
        ),
        interpret=interpret,
    )(jnp.moveaxis(anchors.astype(jnp.float32), 0, 1), gt, packed_t)

    matched_boxes = (
        out[:, :4, :] if planar else jnp.moveaxis(out[:, :4, :], 1, 2)
    )
    matched_labels = out[:, 4, :].astype(jnp.int32)
    max_iou = out[:, ROW_MAX_IOU, :]
    gt_best_iou = gtbest[..., GT_COL_IOU]
    gt_best_anchor = gtbest[..., GT_COL_ANCHOR].astype(jnp.int32)
    return matched_boxes, matched_labels, max_iou, gt_best_iou, gt_best_anchor
