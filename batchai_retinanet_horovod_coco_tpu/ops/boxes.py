"""Box encode/decode between corner boxes and regression deltas, plus clipping.

Replaces keras-retinanet's ``bbox_transform`` / ``RegressBoxes`` / ``ClipBoxes``
(SURVEY.md M5).  We use the standard center-form parametrization
(dx, dy, dw, dh) with normalization stds — a deliberate redesign (the reference
used corner-form deltas); the two are equivalent in expressive power and the
center form is the widely validated detectron recipe.

All functions are pure jnp and shape-preserving, safe under jit/vmap.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class BoxCodecConfig:
    means: tuple[float, float, float, float] = (0.0, 0.0, 0.0, 0.0)
    stds: tuple[float, float, float, float] = (0.1, 0.1, 0.2, 0.2)
    # Clamp on dw/dh before exp, to keep decode finite for garbage logits.
    max_log_scale: float = 4.135  # log(1000/16), detectron convention


def _to_center_form(boxes: jnp.ndarray) -> tuple[jnp.ndarray, ...]:
    w = boxes[..., 2] - boxes[..., 0]
    h = boxes[..., 3] - boxes[..., 1]
    cx = boxes[..., 0] + 0.5 * w
    cy = boxes[..., 1] + 0.5 * h
    return cx, cy, w, h


def encode_boxes(
    anchors: jnp.ndarray,
    gt_boxes: jnp.ndarray,
    config: BoxCodecConfig = BoxCodecConfig(),
) -> jnp.ndarray:
    """Regression targets for ``gt_boxes`` w.r.t. ``anchors``; both (..., 4)."""
    acx, acy, aw, ah = _to_center_form(anchors)
    gcx, gcy, gw, gh = _to_center_form(gt_boxes)
    # Guard against degenerate (padded) boxes; callers mask these out.
    aw = jnp.maximum(aw, 1e-6)
    ah = jnp.maximum(ah, 1e-6)
    gw = jnp.maximum(gw, 1e-6)
    gh = jnp.maximum(gh, 1e-6)
    deltas = jnp.stack(
        [
            (gcx - acx) / aw,
            (gcy - acy) / ah,
            jnp.log(gw / aw),
            jnp.log(gh / ah),
        ],
        axis=-1,
    )
    means = jnp.asarray(config.means, dtype=deltas.dtype)
    stds = jnp.asarray(config.stds, dtype=deltas.dtype)
    return (deltas - means) / stds


def encode_boxes_planar(
    anchors: jnp.ndarray,
    gt_boxes: jnp.ndarray,
    config: BoxCodecConfig = BoxCodecConfig(),
) -> jnp.ndarray:
    """:func:`encode_boxes` on coordinate-planar (..., 4, A) tensors.

    TPU layout form: the coordinate axis rides sublanes and anchors ride the
    128-lane minor dim, so every op runs full-lane and nothing pays the 32x
    lane-padding tax of a 4-minor tensor (a (B, A, 4) f32 tensor at the
    flagship bucket is 6.45 MB logical but ~206 MB as T(8,128) tiles).
    Same arithmetic per element as :func:`encode_boxes` → identical values.
    """

    def center(b):
        w = b[..., 2, :] - b[..., 0, :]
        h = b[..., 3, :] - b[..., 1, :]
        return b[..., 0, :] + 0.5 * w, b[..., 1, :] + 0.5 * h, w, h

    acx, acy, aw, ah = center(anchors)
    gcx, gcy, gw, gh = center(gt_boxes)
    aw = jnp.maximum(aw, 1e-6)
    ah = jnp.maximum(ah, 1e-6)
    gw = jnp.maximum(gw, 1e-6)
    gh = jnp.maximum(gh, 1e-6)
    deltas = jnp.stack(
        [
            (gcx - acx) / aw,
            (gcy - acy) / ah,
            jnp.log(gw / aw),
            jnp.log(gh / ah),
        ],
        axis=-2,
    )
    means = jnp.asarray(config.means, dtype=deltas.dtype)[:, None]
    stds = jnp.asarray(config.stds, dtype=deltas.dtype)[:, None]
    return (deltas - means) / stds


def decode_boxes(
    anchors: jnp.ndarray,
    deltas: jnp.ndarray,
    config: BoxCodecConfig = BoxCodecConfig(),
) -> jnp.ndarray:
    """Inverse of :func:`encode_boxes`: (..., 4) deltas → corner boxes."""
    means = jnp.asarray(config.means, dtype=deltas.dtype)
    stds = jnp.asarray(config.stds, dtype=deltas.dtype)
    deltas = deltas * stds + means
    acx, acy, aw, ah = _to_center_form(anchors)
    dx, dy, dw, dh = (deltas[..., i] for i in range(4))
    dw = jnp.clip(dw, max=config.max_log_scale)
    dh = jnp.clip(dh, max=config.max_log_scale)
    cx = acx + dx * aw
    cy = acy + dy * ah
    w = aw * jnp.exp(dw)
    h = ah * jnp.exp(dh)
    return jnp.stack(
        [cx - 0.5 * w, cy - 0.5 * h, cx + 0.5 * w, cy + 0.5 * h], axis=-1
    )


def clip_boxes(boxes: jnp.ndarray, image_hw: tuple[int, int]) -> jnp.ndarray:
    """Clip corner boxes to [0, W] x [0, H]."""
    h, w = image_hw
    x1 = jnp.clip(boxes[..., 0], 0.0, float(w))
    y1 = jnp.clip(boxes[..., 1], 0.0, float(h))
    x2 = jnp.clip(boxes[..., 2], 0.0, float(w))
    y2 = jnp.clip(boxes[..., 3], 0.0, float(h))
    return jnp.stack([x1, y1, x2, y2], axis=-1)
